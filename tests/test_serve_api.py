"""OpenAI API surface tests over a tiny CPU-mesh engine.

Exercises the model-server contract the reference router depends on
(docs/architecture/core/model-servers.md:38-100): completions (stream +
non-stream), chat, models, health, metrics scrape, render/tokenize.
"""

import json

import pytest
from aiohttp.test_utils import TestClient, TestServer

pytestmark = pytest.mark.anyio


@pytest.fixture
def anyio_backend():
    return "asyncio"

from llmd_tpu.config import CacheConfig, EngineConfig, SchedulerConfig, tiny_model_config
from llmd_tpu.engine import LLMEngine
from llmd_tpu.serve.api import build_app
from llmd_tpu.serve.async_engine import AsyncEngine
from llmd_tpu.serve.tokenizer import ByteTokenizer


def make_engine(**model_overrides) -> LLMEngine:
    cfg = EngineConfig(
        model=tiny_model_config(vocab_size=512, max_model_len=128, **model_overrides),
        cache=CacheConfig(page_size=4, num_blocks=128, dtype="float32"),
        scheduler=SchedulerConfig(max_num_seqs=8, max_num_batched_tokens=64),
    )
    return LLMEngine(cfg)


@pytest.fixture
async def client():
    engine = make_engine()
    app = build_app(AsyncEngine(engine), ByteTokenizer(), "tiny", 128)
    c = TestClient(TestServer(app))
    await c.start_server()
    yield c
    await c.close()


async def test_health_and_models(client):
    r = await client.get("/health")
    assert r.status == 200
    r = await client.get("/v1/models")
    data = await r.json()
    assert data["data"][0]["id"] == "tiny"
    assert data["data"][0]["max_model_len"] == 128


async def test_completion_basic(client):
    r = await client.post(
        "/v1/completions",
        json={"prompt": "hello world", "max_tokens": 8, "temperature": 0.0},
    )
    assert r.status == 200
    data = await r.json()
    assert data["object"] == "text_completion"
    assert data["usage"]["completion_tokens"] >= 1
    assert data["choices"][0]["finish_reason"] in ("length", "stop")


async def test_completion_token_ids_prompt(client):
    r = await client.post(
        "/v1/completions",
        json={"prompt": [5, 6, 7, 8], "max_tokens": 4, "temperature": 0.0},
    )
    data = await r.json()
    assert r.status == 200, data
    assert data["usage"]["prompt_tokens"] == 4


async def test_completion_streaming(client):
    r = await client.post(
        "/v1/completions",
        json={"prompt": "abc", "max_tokens": 6, "temperature": 0.0, "stream": True},
    )
    assert r.status == 200
    assert r.headers["Content-Type"].startswith("text/event-stream")
    chunks = []
    async for line in r.content:
        line = line.decode().strip()
        if not line.startswith("data: "):
            continue
        payload = line[len("data: ") :]
        if payload == "[DONE]":
            break
        chunks.append(json.loads(payload))
    assert chunks, "no SSE chunks"
    assert chunks[-1]["choices"][0]["finish_reason"] in ("length", "stop")
    assert "usage" in chunks[-1]


async def test_chat_completion(client):
    r = await client.post(
        "/v1/chat/completions",
        json={
            "messages": [{"role": "user", "content": "hi"}],
            "max_tokens": 5,
            "temperature": 0.0,
        },
    )
    assert r.status == 200
    data = await r.json()
    assert data["object"] == "chat.completion"
    assert data["choices"][0]["message"]["role"] == "assistant"


async def test_metrics_scrape(client):
    await client.post(
        "/v1/completions", json={"prompt": "xy", "max_tokens": 3, "temperature": 0.0}
    )
    r = await client.get("/metrics")
    text = await r.text()
    assert "vllm:num_requests_waiting" in text
    assert "llmd:generation_tokens_total" in text
    from llmd_tpu.serve.metrics import parse_prometheus

    parsed = parse_prometheus(text)
    assert parsed["vllm:generation_tokens_total"] >= 3


async def test_render_endpoints(client):
    r = await client.post("/v1/completions/render", json={"prompt": "hello"})
    data = await r.json()
    ids = data["prompt_token_ids"]
    assert ids == ByteTokenizer().encode("hello")
    r = await client.post(
        "/v1/chat/completions/render",
        json={"messages": [{"role": "user", "content": "hello"}]},
    )
    data = await r.json()
    assert len(data["prompt_token_ids"]) > 5


async def test_validation_errors(client):
    r = await client.post("/v1/completions", json={"prompt": [], "max_tokens": 2})
    assert r.status == 400
    r = await client.post(
        "/v1/completions", json={"prompt": "x" * 500, "max_tokens": 2}
    )
    assert r.status == 400
    r = await client.post(
        "/v1/completions", json={"prompt": "ok", "n": 0, "max_tokens": 2}
    )
    assert r.status == 400


async def test_stop_token_ids(client):
    # Greedy decoding with every possible token as a stop => stops at 1 token.
    r = await client.post(
        "/v1/completions",
        json={
            "prompt": "hello",
            "max_tokens": 10,
            "temperature": 0.0,
            "stop_token_ids": list(range(512)),
        },
    )
    data = await r.json()
    assert data["choices"][0]["finish_reason"] == "stop"
    assert data["usage"]["completion_tokens"] == 1


def test_detokenizer_stop_holdback():
    from llmd_tpu.serve.api import Detokenizer

    tok = ByteTokenizer()
    # "ab" is the stop; feed "x", "a", "b" one token at a time.
    d = Detokenizer(tok, ["ab"])
    deltas = [d.feed(tok.encode("x", add_special_tokens=False))]
    deltas.append(d.feed(tok.encode("a", add_special_tokens=False)))
    assert "a" not in "".join(deltas), "stop-prefix leaked to the stream"
    deltas.append(d.feed(tok.encode("b", add_special_tokens=False)))
    assert d.stopped
    assert "".join(deltas) == "x"
    # Earliest occurrence across stops wins, not first-in-list.
    d2 = Detokenizer(tok, ["zzz", "c"])
    d2.feed(tok.encode("abczzz", add_special_tokens=False), final=True)
    assert d2.stopped and d2.emitted == "ab"
    # Holdback is flushed when generation finishes without a stop match.
    d3 = Detokenizer(tok, ["QQ"])
    out = d3.feed(tok.encode("hel", add_special_tokens=False))
    out += d3.feed(tok.encode("lo", add_special_tokens=False), final=True)
    assert out == "hello"


async def test_concurrent_requests(client):
    import asyncio

    async def one(i):
        r = await client.post(
            "/v1/completions",
            json={"prompt": f"prompt number {i}", "max_tokens": 4, "temperature": 0.0},
        )
        assert r.status == 200
        return await r.json()

    results = await asyncio.gather(*[one(i) for i in range(6)])
    assert all(r["usage"]["completion_tokens"] >= 1 for r in results)


async def test_embeddings_endpoint(client):
    import math

    # string input
    r = await client.post("/v1/embeddings", json={"model": "tiny", "input": "hello world"})
    assert r.status == 200, await r.text()
    d = await r.json()
    v1 = d["data"][0]["embedding"]
    assert d["object"] == "list" and d["data"][0]["index"] == 0
    tok = await client.post("/tokenize", json={"prompt": "hello world"})
    assert d["usage"]["prompt_tokens"] == (await tok.json())["count"]
    # unit norm
    assert abs(math.sqrt(sum(x * x for x in v1)) - 1.0) < 1e-4

    # deterministic + input-sensitive
    r = await client.post("/v1/embeddings", json={"input": "hello world"})
    assert (await r.json())["data"][0]["embedding"] == v1
    r = await client.post("/v1/embeddings", json={"input": "different text"})
    v2 = (await r.json())["data"][0]["embedding"]
    assert v2 != v1

    # batch of strings: rows match the single calls
    r = await client.post(
        "/v1/embeddings", json={"input": ["hello world", "different text"]}
    )
    d = await r.json()
    assert len(d["data"]) == 2
    import numpy as np

    np.testing.assert_allclose(d["data"][0]["embedding"], v1, atol=1e-5)
    np.testing.assert_allclose(d["data"][1]["embedding"], v2, atol=1e-5)

    # token-array input == its string equivalent (tokenize first: the
    # byte tokenizer may add special tokens)
    ids = (await (await client.post(
        "/tokenize", json={"prompt": "hello world"}
    )).json())["tokens"]
    r = await client.post("/v1/embeddings", json={"input": ids})
    np.testing.assert_allclose(
        (await r.json())["data"][0]["embedding"], v1, atol=1e-5
    )

    # validation
    r = await client.post("/v1/embeddings", json={"input": []})
    assert r.status == 400
    r = await client.post("/v1/embeddings", json={"input": {"bad": 1}})
    assert r.status == 400
    r = await client.post("/v1/embeddings", json={"input": "x" * 4096})
    assert r.status == 400  # over the embed length limit
    r = await client.post("/v1/embeddings", json=[1, 2])  # non-object body
    assert r.status == 400

    # batches larger than max_num_seqs slice internally (engine max is 8)
    r = await client.post(
        "/v1/embeddings", json={"input": [f"text {i}" for i in range(11)]}
    )
    assert r.status == 200, await r.text()
    d = await r.json()
    assert len(d["data"]) == 11
    r1 = await client.post("/v1/embeddings", json={"input": "text 9"})
    np.testing.assert_allclose(
        d["data"][9]["embedding"],
        (await r1.json())["data"][0]["embedding"], atol=1e-5,
    )


async def test_embeddings_model_validation_with_adapters():
    """Embeddings enforce the same model-id discipline as generation:
    adapter ids embed through their slot, unknown ids 404."""
    engine = make_engine(num_lora_adapters=1, lora_rank=4)
    app = build_app(
        AsyncEngine(engine), ByteTokenizer(), "tiny", 128,
        lora_adapters={"ad": 1},
    )
    c = TestClient(TestServer(app))
    await c.start_server()
    try:
        r = await c.post("/v1/embeddings", json={"model": "typo", "input": "x"})
        assert r.status == 404
        r = await c.post("/v1/embeddings", json={"model": "ad", "input": "x"})
        assert r.status == 200, await r.text()
    finally:
        await c.close()


async def test_grpc_embed_endpoint(client):
    ids = [ord(c) for c in "token surface"]
    r = await client.post("/vllm.Generation/Embed", json={"prompt_token_ids": ids})
    assert r.status == 200, await r.text()
    d = await r.json()
    assert len(d["embeddings"]) == 1
    # matches the OpenAI surface for the same tokens
    r2 = await client.post("/v1/embeddings", json={"input": ids})
    import numpy as np

    np.testing.assert_allclose(
        d["embeddings"][0], (await r2.json())["data"][0]["embedding"], atol=1e-5
    )


async def test_completion_n_choices(client):
    # n seeded samples: reproducible, indexed, usage sums choices
    r = await client.post("/v1/completions", json={
        "model": "tiny", "prompt": "hello", "max_tokens": 5,
        "n": 3, "temperature": 1.0, "seed": 42,
    })
    assert r.status == 200, await r.text()
    d = await r.json()
    assert [c["index"] for c in d["choices"]] == [0, 1, 2]
    # usage sums ALL choices: at least 1 token each, at most max_tokens
    assert 3 <= d["usage"]["completion_tokens"] <= 3 * 5
    texts = [c["text"] for c in d["choices"]]
    # seeded: same request reproduces the same choice set
    r2 = await client.post("/v1/completions", json={
        "model": "tiny", "prompt": "hello", "max_tokens": 5,
        "n": 3, "temperature": 1.0, "seed": 42,
    })
    assert [c["text"] for c in (await r2.json())["choices"]] == texts
    # seed+i derivation: choices differ from each other (overwhelmingly)
    assert len(set(texts)) > 1

    # greedy n: all choices identical (OpenAI semantics)
    r = await client.post("/v1/completions", json={
        "prompt": "hello", "max_tokens": 4, "n": 2, "temperature": 0.0,
    })
    d = await r.json()
    assert d["choices"][0]["text"] == d["choices"][1]["text"]

    # chat n
    r = await client.post("/v1/chat/completions", json={
        "messages": [{"role": "user", "content": "hi"}],
        "max_tokens": 4, "n": 2, "temperature": 1.0, "seed": 7,
    })
    assert r.status == 200, await r.text()
    d = await r.json()
    assert len(d["choices"]) == 2
    assert all("content" in c["message"] for c in d["choices"])

    # limits
    r = await client.post("/v1/completions", json={
        "prompt": "x", "n": 99,
    })
    assert r.status == 400
    # streaming with n>1 is now a supported surface (interleaved SSE,
    # covered by test_streaming_n_gt_1_interleaves_choices)
    r = await client.post("/v1/completions", json={
        "prompt": "x", "n": 2, "stream": True, "max_tokens": 2,
    })
    assert r.status == 200
    async for _ in r.content:
        pass


async def test_streaming_n_gt_1_interleaves_choices(client):
    """SSE with n>1 (reference capability the round-2 build rejected):
    every choice index streams deltas and a finish chunk; the final frame
    aggregates usage across choices."""
    r = await client.post(
        "/v1/completions",
        json={"prompt": "abc", "max_tokens": 5, "temperature": 0.8,
              "seed": 7, "n": 3, "stream": True},
    )
    assert r.status == 200
    chunks = []
    async for line in r.content:
        line = line.decode().strip()
        if not line.startswith("data: "):
            continue
        payload = line[len("data: "):]
        if payload == "[DONE]":
            break
        chunks.append(json.loads(payload))
    indices = {c["choices"][0]["index"] for c in chunks if c.get("choices")}
    assert indices == {0, 1, 2}
    finishes = [
        c["choices"][0] for c in chunks
        if c.get("choices") and c["choices"][0].get("finish_reason")
    ]
    assert len(finishes) == 3
    assert {f["index"] for f in finishes} == {0, 1, 2}
    assert chunks[-1]["usage"]["completion_tokens"] == 15


async def test_streaming_chat_n_gt_1(client):
    r = await client.post(
        "/v1/chat/completions",
        json={"messages": [{"role": "user", "content": "hi"}],
              "max_tokens": 3, "temperature": 0.9, "n": 2, "stream": True},
    )
    assert r.status == 200
    roles, finishes = set(), set()
    async for line in r.content:
        line = line.decode().strip()
        if not line.startswith("data: ") or line.endswith("[DONE]"):
            continue
        c = json.loads(line[len("data: "):])
        for ch in c.get("choices", []):
            if ch.get("delta", {}).get("role"):
                roles.add(ch["index"])
            if ch.get("finish_reason"):
                finishes.add(ch["index"])
    assert roles == {0, 1}
    assert finishes == {0, 1}


async def test_responses_create_retrieve_delete(client):
    r = await client.post(
        "/v1/responses",
        json={"model": "tiny", "input": "hello there",
              "max_output_tokens": 6, "temperature": 0.0},
    )
    assert r.status == 200
    data = await r.json()
    assert data["object"] == "response"
    assert data["status"] == "completed"
    assert data["output"][0]["content"][0]["type"] == "output_text"
    assert data["usage"]["output_tokens"] >= 1
    rid = data["id"]

    r = await client.get(f"/v1/responses/{rid}")
    assert r.status == 200
    assert (await r.json())["id"] == rid

    r = await client.delete(f"/v1/responses/{rid}")
    assert (await r.json())["deleted"] is True
    r = await client.get(f"/v1/responses/{rid}")
    assert r.status == 404


async def test_responses_previous_response_chaining(client):
    r = await client.post(
        "/v1/responses",
        json={"model": "tiny", "input": "first turn", "max_output_tokens": 4,
              "temperature": 0.0},
    )
    first = await r.json()
    r = await client.post(
        "/v1/responses",
        json={"model": "tiny", "input": "second turn",
              "previous_response_id": first["id"],
              "max_output_tokens": 4, "temperature": 0.0},
    )
    assert r.status == 200
    second = await r.json()
    # chained: the second request's input tokens include the first turn
    assert second["usage"]["input_tokens"] > first["usage"]["input_tokens"]
    # unknown previous id is a client error
    r = await client.post(
        "/v1/responses",
        json={"model": "tiny", "input": "x", "previous_response_id": "resp_nope"},
    )
    assert r.status == 404


async def test_responses_streaming_events(client):
    r = await client.post(
        "/v1/responses",
        json={"model": "tiny", "input": "stream me",
              "max_output_tokens": 5, "temperature": 0.0, "stream": True},
    )
    assert r.status == 200
    events = []
    cur_event = None
    async for line in r.content:
        line = line.decode().strip()
        if line.startswith("event: "):
            cur_event = line[len("event: "):]
        elif line.startswith("data: ") and cur_event:
            events.append((cur_event, json.loads(line[len("data: "):])))
    names = [e for e, _ in events]
    assert names[0] == "response.created"
    assert "response.output_text.delta" in names
    assert names[-1] == "response.completed"
    final = events[-1][1]["response"]
    assert final["status"] == "completed"
    assert final["output"][0]["content"][0]["text"]


async def test_conversations_flow(client):
    r = await client.post("/v1/conversations", json={"metadata": {"t": "1"}})
    conv = await r.json()
    assert conv["object"] == "conversation"
    cid = conv["id"]

    r = await client.post(
        f"/v1/conversations/{cid}/items",
        json={"items": [{"type": "message", "role": "user",
                         "content": "remember me"}]},
    )
    assert r.status == 200
    r = await client.get(f"/v1/conversations/{cid}/items")
    items = (await r.json())["data"]
    assert items[0]["content"] == "remember me"

    # a response in the conversation consumes + appends its turns
    r = await client.post(
        "/v1/responses",
        json={"model": "tiny", "input": "and this", "conversation": cid,
              "max_output_tokens": 4, "temperature": 0.0},
    )
    assert r.status == 200
    r = await client.get(f"/v1/conversations/{cid}/items")
    items = (await r.json())["data"]
    assert items[-1]["role"] == "assistant"
    # unknown conversation 404s
    r = await client.post(
        "/v1/responses", json={"model": "tiny", "input": "x",
                               "conversation": "conv_nope"},
    )
    assert r.status == 404


def test_deliver_is_atomic_against_same_id_reregistration():
    """Regression: _deliver (engine thread) must hold the lock across
    its get/pop of _subs. Unlocked, a loop-thread abort+resubmit of the
    same request id could interleave between the get and the pop, and
    the pop would silently drop the NEW stream's queue — the resubmitted
    request would hang forever. Surfaced by the CC001 guarded-by triage
    (static-analysis.md)."""
    import asyncio
    import threading

    from llmd_tpu.engine.request import RequestOutput

    class _StubEngine:
        stats = None

        def has_work(self):
            return False

    inst = AsyncEngine(_StubEngine())
    loop = asyncio.new_event_loop()
    try:
        inst._loop = loop
        rid = "req-1"
        inst.submit(rid, [1, 2, 3], None)

        windows = threading.Event()   # _deliver is inside its window
        resubmitted = threading.Event()

        class _RacingDict(dict):
            def get(self, k, default=None):
                out = dict.get(self, k, default)
                if k == rid and not windows.is_set():
                    windows.set()
                    # Give the racer the whole window between the get
                    # and the pop. With _deliver holding the lock the
                    # racer stays blocked and this times out; unlocked,
                    # the racer swaps in the new queue mid-window.
                    resubmitted.wait(0.3)
                return out

        with inst._lock:
            inst._subs = _RacingDict(inst._subs)

        def racer():
            windows.wait(2)
            inst.abort(rid)            # client disconnected...
            inst.submit(rid, [4], None)  # ...and retried with the same id
            resubmitted.set()

        t = threading.Thread(target=racer)
        t.start()
        final = RequestOutput(
            request_id=rid, new_token_ids=[7], finished=True,
            finish_reason="stop", num_prompt_tokens=3, num_output_tokens=1,
        )
        inst._deliver(rid, final)  # engine-thread side
        t.join(timeout=5)
        assert resubmitted.is_set()
        # The resubmitted stream's queue must have survived the pop.
        with inst._lock:
            assert rid in inst._subs
    finally:
        inst._fetch_pool.shutdown(wait=False, cancel_futures=True)
        loop.close()
