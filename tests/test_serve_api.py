"""OpenAI API surface tests over a tiny CPU-mesh engine.

Exercises the model-server contract the reference router depends on
(docs/architecture/core/model-servers.md:38-100): completions (stream +
non-stream), chat, models, health, metrics scrape, render/tokenize.
"""

import json

import pytest
from aiohttp.test_utils import TestClient, TestServer

pytestmark = pytest.mark.anyio


@pytest.fixture
def anyio_backend():
    return "asyncio"

from llmd_tpu.config import CacheConfig, EngineConfig, SchedulerConfig, tiny_model_config
from llmd_tpu.engine import LLMEngine
from llmd_tpu.serve.api import build_app
from llmd_tpu.serve.async_engine import AsyncEngine
from llmd_tpu.serve.tokenizer import ByteTokenizer


def make_engine(**model_overrides) -> LLMEngine:
    cfg = EngineConfig(
        model=tiny_model_config(vocab_size=512, max_model_len=128, **model_overrides),
        cache=CacheConfig(page_size=4, num_blocks=128, dtype="float32"),
        scheduler=SchedulerConfig(max_num_seqs=8, max_num_batched_tokens=64),
    )
    return LLMEngine(cfg)


@pytest.fixture
async def client():
    engine = make_engine()
    app = build_app(AsyncEngine(engine), ByteTokenizer(), "tiny", 128)
    c = TestClient(TestServer(app))
    await c.start_server()
    yield c
    await c.close()


async def test_health_and_models(client):
    r = await client.get("/health")
    assert r.status == 200
    r = await client.get("/v1/models")
    data = await r.json()
    assert data["data"][0]["id"] == "tiny"
    assert data["data"][0]["max_model_len"] == 128


async def test_completion_basic(client):
    r = await client.post(
        "/v1/completions",
        json={"prompt": "hello world", "max_tokens": 8, "temperature": 0.0},
    )
    assert r.status == 200
    data = await r.json()
    assert data["object"] == "text_completion"
    assert data["usage"]["completion_tokens"] >= 1
    assert data["choices"][0]["finish_reason"] in ("length", "stop")


async def test_completion_token_ids_prompt(client):
    r = await client.post(
        "/v1/completions",
        json={"prompt": [5, 6, 7, 8], "max_tokens": 4, "temperature": 0.0},
    )
    data = await r.json()
    assert r.status == 200, data
    assert data["usage"]["prompt_tokens"] == 4


async def test_completion_streaming(client):
    r = await client.post(
        "/v1/completions",
        json={"prompt": "abc", "max_tokens": 6, "temperature": 0.0, "stream": True},
    )
    assert r.status == 200
    assert r.headers["Content-Type"].startswith("text/event-stream")
    chunks = []
    async for line in r.content:
        line = line.decode().strip()
        if not line.startswith("data: "):
            continue
        payload = line[len("data: ") :]
        if payload == "[DONE]":
            break
        chunks.append(json.loads(payload))
    assert chunks, "no SSE chunks"
    assert chunks[-1]["choices"][0]["finish_reason"] in ("length", "stop")
    assert "usage" in chunks[-1]


async def test_chat_completion(client):
    r = await client.post(
        "/v1/chat/completions",
        json={
            "messages": [{"role": "user", "content": "hi"}],
            "max_tokens": 5,
            "temperature": 0.0,
        },
    )
    assert r.status == 200
    data = await r.json()
    assert data["object"] == "chat.completion"
    assert data["choices"][0]["message"]["role"] == "assistant"


async def test_metrics_scrape(client):
    await client.post(
        "/v1/completions", json={"prompt": "xy", "max_tokens": 3, "temperature": 0.0}
    )
    r = await client.get("/metrics")
    text = await r.text()
    assert "vllm:num_requests_waiting" in text
    assert "llmd:generation_tokens_total" in text
    from llmd_tpu.serve.metrics import parse_prometheus

    parsed = parse_prometheus(text)
    assert parsed["vllm:generation_tokens_total"] >= 3


async def test_render_endpoints(client):
    r = await client.post("/v1/completions/render", json={"prompt": "hello"})
    data = await r.json()
    ids = data["prompt_token_ids"]
    assert ids == ByteTokenizer().encode("hello")
    r = await client.post(
        "/v1/chat/completions/render",
        json={"messages": [{"role": "user", "content": "hello"}]},
    )
    data = await r.json()
    assert len(data["prompt_token_ids"]) > 5


async def test_validation_errors(client):
    r = await client.post("/v1/completions", json={"prompt": [], "max_tokens": 2})
    assert r.status == 400
    r = await client.post(
        "/v1/completions", json={"prompt": "x" * 500, "max_tokens": 2}
    )
    assert r.status == 400
    r = await client.post(
        "/v1/completions", json={"prompt": "ok", "n": 3, "max_tokens": 2}
    )
    assert r.status == 400


async def test_stop_token_ids(client):
    # Greedy decoding with every possible token as a stop => stops at 1 token.
    r = await client.post(
        "/v1/completions",
        json={
            "prompt": "hello",
            "max_tokens": 10,
            "temperature": 0.0,
            "stop_token_ids": list(range(512)),
        },
    )
    data = await r.json()
    assert data["choices"][0]["finish_reason"] == "stop"
    assert data["usage"]["completion_tokens"] == 1


def test_detokenizer_stop_holdback():
    from llmd_tpu.serve.api import Detokenizer

    tok = ByteTokenizer()
    # "ab" is the stop; feed "x", "a", "b" one token at a time.
    d = Detokenizer(tok, ["ab"])
    deltas = [d.feed(tok.encode("x", add_special_tokens=False))]
    deltas.append(d.feed(tok.encode("a", add_special_tokens=False)))
    assert "a" not in "".join(deltas), "stop-prefix leaked to the stream"
    deltas.append(d.feed(tok.encode("b", add_special_tokens=False)))
    assert d.stopped
    assert "".join(deltas) == "x"
    # Earliest occurrence across stops wins, not first-in-list.
    d2 = Detokenizer(tok, ["zzz", "c"])
    d2.feed(tok.encode("abczzz", add_special_tokens=False), final=True)
    assert d2.stopped and d2.emitted == "ab"
    # Holdback is flushed when generation finishes without a stop match.
    d3 = Detokenizer(tok, ["QQ"])
    out = d3.feed(tok.encode("hel", add_special_tokens=False))
    out += d3.feed(tok.encode("lo", add_special_tokens=False), final=True)
    assert out == "hello"


async def test_concurrent_requests(client):
    import asyncio

    async def one(i):
        r = await client.post(
            "/v1/completions",
            json={"prompt": f"prompt number {i}", "max_tokens": 4, "temperature": 0.0},
        )
        assert r.status == 200
        return await r.json()

    results = await asyncio.gather(*[one(i) for i in range(6)])
    assert all(r["usage"]["completion_tokens"] >= 1 for r in results)
