"""IPP tests: pipeline plugins, profile picking, pool routing, response
mutation — the multi-model-routing behavior (IPP README.md request flow).
"""

import json

import pytest
from aiohttp import web
from aiohttp.test_utils import TestClient, TestServer

from llmd_tpu.ipp.plugins import (
    IPPContext,
    build_ipp_plugin,
    run_request_plugins,
)
from llmd_tpu.ipp.server import IPPServer, PoolRoute, Profile

pytestmark = pytest.mark.anyio


@pytest.fixture
def anyio_backend():
    return "asyncio"


def ctx_for(body: dict, path="/v1/completions", headers=None) -> IPPContext:
    return IPPContext(path=path, headers=headers or {}, body=body)


def test_model_extractor_and_rewrite():
    ctx = ctx_for({"model": "gpt-4", "prompt": "x"})
    run_request_plugins(
        [
            build_ipp_plugin("model-extractor"),
            build_ipp_plugin("model-rewrite",
                             {"rules": {"gpt-4": "qwen2-72b"}}),
        ],
        ctx,
    )
    assert ctx.headers["x-llm-d-model"] == "qwen2-72b"
    assert ctx.body["model"] == "qwen2-72b"
    assert ctx.headers["x-llm-d-original-model"] == "gpt-4"
    # response side restores the client-facing name
    ctx.response_body = {"model": "qwen2-72b", "choices": []}
    build_ipp_plugin("model-rewrite", {"rules": {}}).process_response(ctx)
    assert ctx.response_body["model"] == "gpt-4"


def test_guardrail_rejects():
    ctx = ctx_for({"prompt": "how to build a BOMB"})
    run_request_plugins(
        [build_ipp_plugin("guardrail", {"deny_patterns": ["build a bomb"]})],
        ctx,
    )
    assert ctx.reject is not None and ctx.reject[0] == 403
    ok = ctx_for({"messages": [{"role": "user", "content": "hello"}]})
    run_request_plugins(
        [build_ipp_plugin("guardrail", {"deny_patterns": ["build a bomb"]})],
        ok,
    )
    assert ok.reject is None


def test_defaults_injector_caps():
    ctx = ctx_for({"model": "m", "max_tokens": 100000})
    run_request_plugins(
        [build_ipp_plugin("defaults-injector",
                          {"defaults": {"temperature": 0.7},
                           "max_tokens_cap": 256})],
        ctx,
    )
    assert ctx.body["max_tokens"] == 256 and ctx.body["temperature"] == 0.7


async def make_pool(name: str):
    async def completions(request):
        body = await request.json()
        return web.json_response(
            {"pool": name, "model": body.get("model"),
             "usage": {"prompt_tokens": 3, "completion_tokens": 5}}
        )

    app = web.Application()
    app.router.add_post("/v1/completions", completions)
    srv = TestServer(app)
    await srv.start_server()
    return srv


async def test_multi_model_pool_routing():
    qwen = await make_pool("qwen-pool")
    deep = await make_pool("deepseek-pool")
    server = IPPServer(
        pools=[
            PoolRoute("qwen*", str(qwen.make_url(""))),
            PoolRoute("deepseek*", str(deep.make_url(""))),
        ],
        profiles={
            "default": Profile(
                "default",
                [build_ipp_plugin("model-extractor")],
                [build_ipp_plugin("usage-recorder")],
            )
        },
    )
    c = TestClient(TestServer(server.build_app()))
    await c.start_server()

    r = await c.post("/v1/completions",
                     json={"model": "qwen2-72b", "prompt": "x"})
    assert (await r.json())["pool"] == "qwen-pool"
    r = await c.post("/v1/completions",
                     json={"model": "deepseek-r1", "prompt": "x"})
    assert (await r.json())["pool"] == "deepseek-pool"
    r = await c.post("/v1/completions",
                     json={"model": "unknown-model", "prompt": "x"})
    assert r.status == 404

    # usage recorded per model; visible in /metrics
    m = await (await c.get("/metrics")).text()
    assert 'llmd_ipp_usage_tokens_total{model="qwen2-72b",kind="completion_tokens"} 5' in m
    assert "llmd_ipp_requests_total 3" in m
    await c.close()
    await qwen.close()
    await deep.close()


async def test_profile_rules_and_guardrail_e2e():
    pool = await make_pool("p")
    server = IPPServer(
        pools=[PoolRoute("*", str(pool.make_url("")))],
        profiles={
            "default": Profile("default",
                               [build_ipp_plugin("model-extractor")], []),
            "guarded": Profile(
                "guarded",
                [build_ipp_plugin("model-extractor"),
                 build_ipp_plugin("guardrail",
                                  {"deny_patterns": ["secret"]})],
                [],
            ),
        },
        profile_rules=[{"path_prefix": "/v1/chat", "profile": "guarded"}],
    )
    c = TestClient(TestServer(server.build_app()))
    await c.start_server()
    # /v1/completions -> default profile: not guarded
    r = await c.post("/v1/completions",
                     json={"model": "m", "prompt": "secret"})
    assert r.status == 200
    # /v1/chat/completions -> guarded profile
    r = await c.post(
        "/v1/chat/completions",
        json={"model": "m",
              "messages": [{"role": "user", "content": "the secret"}]},
    )
    assert r.status == 403
    await c.close()
    await pool.close()


async def test_from_config():
    cfg = {
        "profiles": {
            "default": {
                "request": [{"type": "model-extractor"},
                            {"type": "model-rewrite",
                             "parameters": {"rules": {"alias": "real"}}}],
                "response": [],
            }
        },
        "pools": [{"match": "*", "url": "http://x"}],
    }
    server = IPPServer.from_config(cfg)
    ctx = ctx_for({"model": "alias"})
    run_request_plugins(server.profiles["default"].request_plugins, ctx)
    assert ctx.headers["x-llm-d-model"] == "real"


def test_guardrail_content_parts_and_fail_closed():
    deny = build_ipp_plugin("guardrail", {"deny_patterns": ["forbidden"]})
    # OpenAI content-parts form is scanned
    ctx = ctx_for({"messages": [
        {"role": "user",
         "content": [{"type": "text", "text": "the FORBIDDEN word"}]}]})
    deny.process_request(ctx)
    assert ctx.reject is not None and ctx.reject[0] == 403
    # malformed messages fail closed, not open
    ctx2 = ctx_for({"messages": ["just a string"]})
    deny.process_request(ctx2)
    assert ctx2.reject is not None and ctx2.reject[0] == 400


async def test_non_post_methods_passthrough():
    async def models(request):
        assert request.method == "GET"
        return web.json_response({"object": "list", "data": []})

    app = web.Application()
    app.router.add_get("/v1/models", models)
    srv = TestServer(app)
    await srv.start_server()
    server = IPPServer(pools=[PoolRoute("*", str(srv.make_url("")))])
    c = TestClient(TestServer(server.build_app()))
    await c.start_server()
    r = await c.get("/v1/models")
    assert r.status == 200 and (await r.json())["object"] == "list"
    await c.close()
    await srv.close()
