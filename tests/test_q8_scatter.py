"""Symmetric q8 KV scatter (runner.scatter_pages_q8 / _OP_KV_SCATTER_Q8).

The import twin of the q8 gather wire: a (q8, wire-scales) bundle lands
host -> HBM without the consumer ever materializing the f32 bundle on
the wire (multi-host broadcasts ride HALF the DCN bytes of the canonical
_OP_KV_SCATTER leg). Float pools dequantize on device; int8 pools take
the bundle byte-direct. The full lockstep leg is exercised by
test_multihost_pd_transfer[int8] where the backend supports it.
"""

import numpy as np
import jax.numpy as jnp

from llmd_tpu.config import (
    CacheConfig, EngineConfig, ParallelConfig, SchedulerConfig,
    tiny_model_config,
)
from llmd_tpu.engine.engine import LLMEngine
from llmd_tpu.engine.runner import _dequantize_rows_q8, _quantize_rows_q8

rng = np.random.default_rng(0)


def make_engine(dtype="float32"):
    cfg = EngineConfig(
        model=tiny_model_config(),
        cache=CacheConfig(page_size=4, num_blocks=32, dtype=dtype),
        scheduler=SchedulerConfig(max_num_seqs=4, max_num_batched_tokens=32),
        parallel=ParallelConfig(tensor_parallel_size=1),
        seed=0,
    )
    return LLMEngine(cfg)


def _wire_bundle(runner, n):
    """A synthetic q8 wire bundle shaped like the producer's gather."""
    L, _, K, page, D2 = runner.gather_pages([0]).shape
    pages = rng.standard_normal((L, n, K, page, D2)).astype(np.float32)
    q8, scales = _quantize_rows_q8(jnp.asarray(pages))
    return np.asarray(q8), np.asarray(scales)


def test_q8_scatter_float_pool_matches_dequant():
    eng = make_engine("float32")
    ids = [3, 7, 11]
    q8, scales = _wire_bundle(eng.runner, len(ids))
    eng.runner.scatter_pages_q8(ids, q8, scales)
    got = eng.runner.gather_pages(ids)
    want = np.asarray(
        _dequantize_rows_q8(jnp.asarray(q8), jnp.asarray(scales), "float32")
    )
    np.testing.assert_allclose(got, want, atol=1e-6, rtol=0)


def test_q8_scatter_matches_canonical_scatter():
    """scatter_pages_q8(bundle) == scatter_pages(dequant(bundle)): the
    wire halving must not change a single pool byte."""
    a, b = make_engine("float32"), make_engine("float32")
    ids = [1, 2, 9, 13]
    q8, scales = _wire_bundle(a.runner, len(ids))
    a.runner.scatter_pages_q8(ids, q8, scales)
    b.runner.scatter_pages(
        ids,
        np.asarray(
            _dequantize_rows_q8(jnp.asarray(q8), jnp.asarray(scales), "float32")
        ),
    )
    np.testing.assert_array_equal(
        a.runner.gather_pages(ids), b.runner.gather_pages(ids)
    )


def test_q8_scatter_int8_pool_direct():
    """Int8 pools take the wire bundle without a dequant/requant round
    trip: a re-gather reproduces the same dequantized rows."""
    eng = make_engine("int8")
    ids = [5, 6]
    q8, scales = _wire_bundle(eng.runner, len(ids))
    eng.runner.scatter_pages_q8(ids, q8, scales)
    got = eng.runner.gather_pages(ids)
    want = np.asarray(
        _dequantize_rows_q8(
            jnp.asarray(q8), jnp.asarray(scales),
            eng.runner.staging_dtype_name,
        )
    )
    np.testing.assert_allclose(
        got.astype(np.float32), want.astype(np.float32), atol=2e-2, rtol=0
    )
