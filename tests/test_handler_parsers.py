"""Request-handler parser plugins: openai / vllmgrpc / passthrough.

Reference surface: docs/architecture/core/router/epp/request-handling.md:50-86
names three parser plugins — `openai-parser`, `vllmgrpc-parser`
(Generate/Embed, token-in/token-out only), `passthrough-parser`.
"""

import json

import pytest

from llmd_tpu.epp.handler import (
    PARSERS,
    ParseError,
    openai_parse,
    parse_request,
    passthrough_parse,
    vllmgrpc_parse,
)


def test_registry_names():
    assert set(PARSERS) == {
        "openai-parser",
        "vllmgrpc-parser",
        "passthrough-parser",
    }


def test_vllmgrpc_generate_tokens():
    body = json.dumps(
        {
            "model": "m",
            "prompt_token_ids": [1, 2, 3, 4],
            "sampling_params": {"max_tokens": 8, "priority": 2},
            "stream": True,
        }
    ).encode()
    req = vllmgrpc_parse("/vllm.Generation/Generate", {}, body)
    assert req.prompt_token_ids == [1, 2, 3, 4]
    assert req.approx_prompt_tokens == 4
    assert req.prompt_text == ""
    assert req.model == "m"
    assert req.streaming is True
    assert req.priority == 2


def test_vllmgrpc_rejects_text_prompt():
    with pytest.raises(ParseError):
        vllmgrpc_parse(
            "/vllm.Generation/Generate",
            {},
            json.dumps({"prompt_token_ids": "not tokens"}).encode(),
        )


def test_vllmgrpc_slo_headers():
    req = vllmgrpc_parse(
        "/vllm.Generation/Generate",
        {"X-LLM-D-SLO-TTFT-MS": "150", "x-llm-d-fairness-id": "t1"},
        json.dumps({"token_ids": [5, 6]}).encode(),
    )
    assert req.ttft_slo_ms == 150.0
    assert req.fairness_id == "t1"


def test_passthrough_opaque_body():
    raw = b"\x00\x01binary-not-json"
    req = passthrough_parse(
        "/custom/infer",
        {"x-llm-d-model": "m2", "accept": "text/event-stream"},
        raw,
    )
    assert req.model == "m2"
    assert req.body == {}
    assert req.prompt_text == ""
    assert req.streaming is True


def test_parse_request_dispatch():
    oai = parse_request(
        "/v1/completions", {}, json.dumps({"prompt": "hi", "model": "m"}).encode()
    )
    assert oai.prompt_text == "hi"
    grpc = parse_request(
        "/vllm.Generation/Embed", {}, json.dumps({"token_ids": [9]}).encode()
    )
    assert grpc.prompt_token_ids == [9]
    # unknown path + passthrough default -> headers-only request
    pt = parse_request("/x", {"x-llm-d-model": "m3"}, b"{}", "passthrough-parser")
    assert pt.model == "m3"
    # unknown path + openai default parses the JSON body
    oai2 = parse_request("/x", {}, json.dumps({"prompt": "p"}).encode())
    assert oai2.prompt_text == "p"


def test_openai_parse_responses_structured_input():
    body = json.dumps(
        {
            "model": "m",
            "input": [{"role": "user", "content": "hello"}],
        }
    ).encode()
    req = openai_parse("/v1/responses", {}, body)
    assert "hello" in req.prompt_text
