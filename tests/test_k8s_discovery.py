"""Watch-based k8s pod discovery (reference k8s-notification-source,
datalayer.md:49-91) + InferencePool binding (inferencepool.md:26-37),
against a simulated API server: LIST seeding, chunked WATCH events,
resourceVersion resume after stream close, 410 Gone -> re-list, and
InferencePool selector/port resolution."""

import asyncio
import json

import pytest
from aiohttp import web
from aiohttp.test_utils import TestServer

from llmd_tpu.epp.datalayer import EndpointStore
from llmd_tpu.epp.k8s_discovery import (
    K8sPodDiscoverySource, resolve_inference_pool,
)

pytestmark = pytest.mark.anyio


@pytest.fixture
def anyio_backend():
    return "asyncio"


def pod(name: str, ip: str, ready: bool = True, rv: str = "1") -> dict:
    return {
        "metadata": {
            "name": name, "resourceVersion": rv,
            "labels": {"llm-d.ai/role": "decode"},
        },
        "spec": {"nodeName": "node-1"},
        "status": {
            "phase": "Running",
            "podIP": ip,
            "conditions": [{"type": "Ready", "status": "True" if ready else "False"}],
        },
    }


class FakeAPIServer:
    """Enough of the pods API for list+watch: scripted watch streams."""

    def __init__(self):
        self.list_pods: list[dict] = []
        self.list_rv = "10"
        # each watch call consumes the next script: list of event dicts,
        # or the string "410" to emit an expired error event
        self.watch_scripts: list = []
        self.watch_queries: list[dict] = []
        self.list_calls = 0
        app = web.Application()
        app.router.add_get("/api/v1/namespaces/ns/pods", self.handle)
        app.router.add_get(
            "/apis/inference.networking.x-k8s.io/v1alpha2/namespaces/ns/"
            "inferencepools/{name}", self.handle_pool,
        )
        self.server = TestServer(app)

    async def handle(self, request: web.Request) -> web.StreamResponse:
        if request.query.get("watch") != "1":
            self.list_calls += 1
            return web.json_response({
                "metadata": {"resourceVersion": self.list_rv},
                "items": self.list_pods,
            })
        self.watch_queries.append(dict(request.query))
        script = self.watch_scripts.pop(0) if self.watch_scripts else []
        resp = web.StreamResponse()
        await resp.prepare(request)
        if script == "410":
            await resp.write(json.dumps({
                "type": "ERROR",
                "object": {"kind": "Status", "code": 410},
            }).encode() + b"\n")
        else:
            for event in script:
                await resp.write(json.dumps(event).encode() + b"\n")
        await resp.write_eof()
        return resp

    async def handle_pool(self, request: web.Request) -> web.Response:
        return web.json_response({
            "spec": {
                "selector": {"llm-d.ai/role": "decode", "app": "m"},
                "targetPortNumber": 9001,
            }
        })

    async def start(self):
        await self.server.start_server()
        return f"http://{self.server.host}:{self.server.port}"


def make_source(store, url, tmp_path, **kw):
    token = tmp_path / "token"
    token.write_text("t0k3n")
    return K8sPodDiscoverySource(
        store,
        label_selector="llm-d.ai/role=decode",
        namespace="ns",
        api_server=url,
        token_path=str(token),
        ca_path=str(tmp_path / "nope.crt"),
        poll_s=0.05,
        **kw,
    )


async def _wait_for(cond, timeout=5.0):
    deadline = asyncio.get_event_loop().time() + timeout
    while asyncio.get_event_loop().time() < deadline:
        if cond():
            return True
        await asyncio.sleep(0.02)
    return False


async def test_watch_applies_events_and_resumes(tmp_path):
    api = FakeAPIServer()
    api.list_pods = [pod("a", "10.0.0.1", rv="9")]
    api.watch_scripts = [
        [
            {"type": "ADDED", "object": pod("b", "10.0.0.2", rv="11")},
            {"type": "MODIFIED", "object": pod("a", "10.0.0.1", ready=False, rv="12")},
        ],
        [],  # resumed stream (asserted via watch_queries)
    ]
    url = await api.start()
    store = EndpointStore()
    src = make_source(store, url, tmp_path)
    task = asyncio.ensure_future(src.run())
    try:
        assert await _wait_for(
            lambda: {e.address for e in store.list()} == {"10.0.0.2:8000"}
        ), [e.address for e in store.list()]
        # second watch resumed from the last event's resourceVersion
        assert await _wait_for(lambda: len(api.watch_queries) >= 2)
        assert api.watch_queries[1]["resourceVersion"] == "12"
        assert api.list_calls == 1  # no re-list on clean close
    finally:
        task.cancel()
        await src.close()
        await api.server.close()


async def test_watch_410_triggers_relist(tmp_path):
    api = FakeAPIServer()
    api.list_pods = [pod("a", "10.0.0.1")]
    api.watch_scripts = ["410", []]
    url = await api.start()
    store = EndpointStore()
    src = make_source(store, url, tmp_path)
    task = asyncio.ensure_future(src.run())
    try:
        assert await _wait_for(lambda: api.list_calls >= 2)
        assert {e.address for e in store.list()} == {"10.0.0.1:8000"}
        # the post-410 watch starts from the fresh list's version
        assert await _wait_for(lambda: len(api.watch_queries) >= 2)
        assert api.watch_queries[1]["resourceVersion"] == api.list_rv
    finally:
        task.cancel()
        await src.close()
        await api.server.close()


async def test_watch_delete_removes_endpoint(tmp_path):
    api = FakeAPIServer()
    api.list_pods = [pod("a", "10.0.0.1", rv="9"), pod("b", "10.0.0.2", rv="9")]
    api.watch_scripts = [
        [{"type": "DELETED", "object": pod("b", "10.0.0.2", rv="11")}],
        [],
    ]
    url = await api.start()
    store = EndpointStore()
    src = make_source(store, url, tmp_path)
    task = asyncio.ensure_future(src.run())
    try:
        assert await _wait_for(
            lambda: {e.address for e in store.list()} == {"10.0.0.1:8000"}
        )
    finally:
        task.cancel()
        await src.close()
        await api.server.close()


async def test_inference_pool_binding(tmp_path):
    api = FakeAPIServer()
    url = await api.start()
    store = EndpointStore()
    src = make_source(store, url, tmp_path)
    try:
        await resolve_inference_pool(src, "llmd-decode-pool")
        assert src.label_selector == "app=m,llm-d.ai/role=decode"
        assert src.target_port == 9001
    finally:
        await src.close()
        await api.server.close()


async def test_poll_mode_still_works(tmp_path):
    api = FakeAPIServer()
    api.list_pods = [pod("a", "10.0.0.1")]
    url = await api.start()
    store = EndpointStore()
    src = make_source(store, url, tmp_path, mode="poll")
    task = asyncio.ensure_future(src.run())
    try:
        assert await _wait_for(
            lambda: {e.address for e in store.list()} == {"10.0.0.1:8000"}
        )
        assert await _wait_for(lambda: api.list_calls >= 2)  # keeps polling
        assert not api.watch_queries
    finally:
        task.cancel()
        await src.close()
        await api.server.close()
