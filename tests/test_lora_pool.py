"""Multi-tenant LoRA: paged adapter pool, runtime load/evict, residency
scoring (docs/architecture/multi-tenant-lora.md).

The pool contract under test: a fixed number of HBM slots over an
unbounded registry, LRU eviction of IDLE adapters only (pinned slots —
referenced by any running or queued row — survive), cold loads parked
at step boundaries instead of stalling the batch, and streams
byte-identical resident-vs-cold-loaded (greedy AND seeded) because the
per-row ``lora_ids`` indirection and the name-salted prefix cache make
slot placement invisible to content.
"""

import threading

import numpy as np
import pytest
from aiohttp.test_utils import TestClient, TestServer

from llmd_tpu.config import (
    CacheConfig,
    EngineConfig,
    SchedulerConfig,
    tiny_model_config,
)
from llmd_tpu.engine import LLMEngine, SamplingParams
from llmd_tpu.lora import (
    AdapterDecodeError,
    AdapterRegistry,
    decode_adapter,
    encode_adapter,
)
from llmd_tpu.lora.source import weights_crc
from llmd_tpu.serve.api import build_app
from llmd_tpu.serve.async_engine import AsyncEngine
from llmd_tpu.serve.tokenizer import ByteTokenizer

pytestmark = pytest.mark.anyio


@pytest.fixture
def anyio_backend():
    return "asyncio"


def _dyn_engine(slots=2, rank=4, **sched):
    model = tiny_model_config(
        name="tiny-lora", num_lora_adapters=slots, lora_rank=rank,
        lora_dynamic=True,
    )
    cfg = EngineConfig(
        model=model,
        cache=CacheConfig(page_size=4, num_blocks=128, dtype="float32"),
        scheduler=SchedulerConfig(
            max_num_seqs=4, max_num_batched_tokens=64,
            **sched,
        ),
    )
    return LLMEngine(cfg)


def _weights(engine, seed, scale=0.5, keys=("la_q", "lb_q", "la_v", "lb_v")):
    layers = engine.runner.params["layers"]
    rng = np.random.default_rng(seed)
    return {
        k: rng.normal(0.0, scale, (layers[k].shape[0], *layers[k].shape[2:]))
        .astype(np.float32)
        for k in keys
    }


def _drain(engine):
    out = {}
    while engine.has_work():
        for res in engine.step():
            out.setdefault(res.request_id, []).extend(res.new_token_ids)
    return out


def _gen(engine, lora_name="", max_tokens=5, seed=None, prompt=None):
    sp = SamplingParams(
        temperature=0.0 if seed is None else 0.8,
        max_tokens=max_tokens, ignore_eos=True, seed=seed,
    )
    rid = engine.add_request(
        prompt or list(range(1, 11)), sp, lora_name=lora_name
    )
    return _drain(engine)[rid]


# --------------------------------------------------------------------- #
# wire framing + registry


def test_adapter_wire_roundtrip_and_crc():
    w = {
        "la_q": np.arange(24, dtype=np.float32).reshape(2, 3, 4),
        "lb_q": np.zeros((2, 4, 3), np.float32),
    }
    blob = encode_adapter(w)
    out = decode_adapter(blob)
    assert set(out) == {"la_q", "lb_q"}
    np.testing.assert_array_equal(out["la_q"], w["la_q"])
    # Flip one payload byte: the CRC must catch it before numpy parses.
    corrupt = bytearray(blob)
    corrupt[len(corrupt) // 2] ^= 0xFF
    with pytest.raises(AdapterDecodeError, match="CRC"):
        decode_adapter(bytes(corrupt))
    with pytest.raises(AdapterDecodeError, match="magic"):
        decode_adapter(b"NOPE!" + blob[5:])
    with pytest.raises(AdapterDecodeError, match="short"):
        decode_adapter(b"xx")


def test_registry_tombstone_detects_weight_change():
    reg = AdapterRegistry()
    w1 = {"la_q": np.ones((1, 2, 2), np.float32)}
    w2 = {"la_q": np.full((1, 2, 2), 2.0, np.float32)}
    _, stale = reg.register("a", w1)
    assert not stale
    with pytest.raises(ValueError, match="already loaded"):
        reg.register("a", w2)
    reg.unregister("a")
    # Same weights back: the name's cached pages are still valid.
    _, stale = reg.register("a", w1)
    assert not stale
    reg.unregister("a")
    # DIFFERENT weights under the same name: stale pages must drop.
    _, stale = reg.register("a", w2)
    assert stale
    assert weights_crc(w1) != weights_crc(w2)


# --------------------------------------------------------------------- #
# pool semantics on the real engine


def test_registry_exceeds_pool_capacity_churn():
    """Five registered tenants over two slots: every request completes,
    residency never exceeds the slot count, eviction provably engages,
    and each adapter keeps its own deterministic stream across
    evictions (the name-salted cache + per-row indirection contract)."""
    engine = _dyn_engine(slots=2)
    names = [f"ad{i}" for i in range(5)]
    for i, n in enumerate(names):
        engine.load_adapter(n, weights=_weights(engine, 100 + i))
    assert engine.adapter_registry.names() == sorted(names)

    first = {n: _gen(engine, lora_name=n) for n in names}
    # Streams are per-adapter functions, not per-slot accidents.
    assert len({tuple(v) for v in first.values()}) == len(names)
    second = {n: _gen(engine, lora_name=n) for n in reversed(names)}
    assert second == first
    pc = engine.adapter_pool.counters()
    assert pc["resident"] <= 2
    assert pc["evictions"] >= 1
    assert pc["cold_loads"] >= 1
    assert engine.stats.lora_pool_resident_adapters <= 2
    assert engine.stats.lora_pool_evictions_total == pc["evictions"]


def test_cold_load_byte_parity_resident_vs_evicted():
    """An adapter's stream is byte-identical whether its weights were
    already resident or had to cold-load into a (different) slot —
    greedy and seeded."""
    for seed in (None, 1234):
        a = _dyn_engine(slots=2)
        wx = _weights(a, 7)
        a.load_adapter("x", weights=wx)  # prefetch-installs into slot 1
        resident_stream = _gen(a, lora_name="x", seed=seed)

        b = _dyn_engine(slots=2)
        b.load_adapter("x", weights=wx)
        # Churn x out of residency with two other tenants...
        b.load_adapter("y", weights=_weights(b, 8))
        _gen(b, lora_name="y", seed=seed)
        b.load_adapter("z", weights=_weights(b, 9))
        _gen(b, lora_name="z", seed=seed)
        assert b.adapter_pool.slot_of("x") is None  # evicted
        # ... then serve x again: parked, cold-loaded, byte-identical.
        cold_stream = _gen(b, lora_name="x", seed=seed)
        assert cold_stream == resident_stream
        assert b.adapter_pool.counters()["cold_loads"] >= 1
        assert b.stats.lora_cold_loads_total >= 1


def test_pinned_slot_survives_eviction_under_load():
    """Both slots pinned by in-flight rows: a third tenant's request
    PARKS (the batch keeps serving) and admits only once a slot goes
    idle — a pinned slot is never evicted mid-stream."""
    engine = _dyn_engine(slots=2)
    for i, n in enumerate(("a", "b", "c")):
        engine.load_adapter(n, weights=_weights(engine, 200 + i))
    long_sp = SamplingParams(temperature=0.0, max_tokens=12, ignore_eos=True)
    short_sp = SamplingParams(temperature=0.0, max_tokens=3, ignore_eos=True)
    prompt = list(range(1, 9))
    out: dict = {}

    def step_into(n=1):
        for _ in range(n):
            for res in engine.step():
                out.setdefault(res.request_id, []).extend(res.new_token_ids)

    ra = engine.add_request(prompt, long_sp, lora_name="a")
    rb = engine.add_request(prompt, short_sp, lora_name="b")
    # One step: a and b are running, pinning both slots.
    step_into()
    slot_a = engine.adapter_pool.slot_of("a")
    slot_b = engine.adapter_pool.slot_of("b")
    assert slot_a is not None and slot_b is not None
    rc = engine.add_request(prompt, short_sp, lora_name="c")
    step_into()
    # c is parked, not running; a and b keep their slots.
    assert engine.adapter_pool.slot_of("c") is None
    assert engine.adapter_pool.slot_of("a") == slot_a
    assert engine.adapter_pool.slot_of("b") == slot_b
    assert engine.adapter_pool.counters()["evictions"] == 0
    assert engine.stats.waiting_lora_adapters == ("c",)

    for rid, toks in _drain(engine).items():
        out.setdefault(rid, []).extend(toks)
    # Everyone finished; c eventually evicted an idle slot (b finishes
    # first: max_tokens 3 < 12), never a pinned one.
    assert len(out[ra]) == 12 and len(out[rb]) == 3 and len(out[rc]) == 3
    pc = engine.adapter_pool.counters()
    assert pc["cold_loads"] >= 1 and pc["evictions"] >= 1
    # The long-running pinned adapter kept its slot throughout.
    assert engine.adapter_pool.slot_of("a") == slot_a


def test_unknown_lora_name_rejected_with_adapter_list():
    engine = _dyn_engine(slots=2)
    engine.load_adapter("known", weights=_weights(engine, 5))
    with pytest.raises(ValueError, match=r"unknown lora_name 'nope'.*known"):
        engine.add_request([1, 2, 3], lora_name="nope")
    # Static engines (no pool): a name without a slot id is the silent-
    # base-model bug — rejected, never served as base.
    static = LLMEngine(EngineConfig(
        model=tiny_model_config(
            name="tiny-lora", num_lora_adapters=1, lora_rank=4
        ),
        cache=CacheConfig(page_size=4, num_blocks=64, dtype="float32"),
        scheduler=SchedulerConfig(max_num_seqs=2, max_num_batched_tokens=64),
    ))
    with pytest.raises(ValueError, match="unknown lora_name 'typo'"):
        static.add_request([1, 2, 3], lora_name="typo")


def test_labels_fresh_before_first_step():
    """An idle engine that just loaded adapters advertises them on the
    very next scrape — the tri-state scorer routes on these labels, so
    they must not wait for the first generate request's step loop."""
    from llmd_tpu.serve.metrics import render_metrics

    engine = _dyn_engine(slots=2)
    engine.load_adapter("warm", weights=_weights(engine, 11))
    assert engine.stats.available_lora_adapters == ("warm",)
    assert engine.stats.resident_lora_adapters == ("warm",)
    assert engine.stats.lora_pool_resident_adapters == 1
    text = render_metrics(engine.stats, "tiny-lora")
    assert 'resident_lora_adapters="warm"' in text
    engine.unload_adapter("warm")
    assert engine.stats.available_lora_adapters == ()
    assert engine.stats.lora_pool_resident_adapters == 0


def test_unload_semantics():
    engine = _dyn_engine(slots=2)
    engine.load_adapter("a", weights=_weights(engine, 1))
    _gen(engine, lora_name="a")
    engine.unload_adapter("a")
    assert engine.adapter_registry.names() == []
    assert engine.adapter_pool.slot_of("a") is None
    with pytest.raises(KeyError):
        engine.unload_adapter("a")
    # Unload refuses while rows reference the adapter.
    engine.load_adapter("b", weights=_weights(engine, 2))
    sp = SamplingParams(temperature=0.0, max_tokens=8, ignore_eos=True)
    engine.add_request([1, 2, 3, 4], sp, lora_name="b")
    engine.step()
    with pytest.raises(RuntimeError, match="in\\s?.?flight|in flight"):
        engine.unload_adapter("b")
    _drain(engine)
    engine.unload_adapter("b")
    # Reload with the SAME weights: cached pages stay valid (tombstone
    # CRC match), and the stream is unchanged.
    engine.load_adapter("c", weights=_weights(engine, 3))
    s1 = _gen(engine, lora_name="c")
    engine.unload_adapter("c")
    engine.load_adapter("c", weights=_weights(engine, 3))
    assert _gen(engine, lora_name="c") == s1


def test_concurrent_load_unload_with_serving():
    """Registry/pool mutations from serving-layer threads race the
    engine thread's resolution path without corruption (the locksan CI
    subset runs this file with the sanitizer armed)."""
    engine = _dyn_engine(slots=2)
    engine.load_adapter("stable", weights=_weights(engine, 50))
    errors = []

    def churn(idx):
        try:
            for i in range(6):
                name = f"t{idx}-{i}"
                engine.load_adapter(name, weights=_weights(engine, idx * 31 + i))
                engine.unload_adapter(name)
        # llmd: allow(broad-except) -- test harness: any failure is re-raised on the main thread below
        except Exception as e:
            errors.append(e)

    threads = [threading.Thread(target=churn, args=(i,)) for i in (1, 2)]
    for t in threads:
        t.start()
    streams = [_gen(engine, lora_name="stable") for _ in range(4)]
    for t in threads:
        t.join()
    assert not errors
    assert len({tuple(s) for s in streams}) == 1
    assert engine.adapter_registry.names() == ["stable"]
    assert engine.adapter_pool.counters()["resident"] <= 2


# --------------------------------------------------------------------- #
# serving surface: the vLLM dynamic-LoRA contract


async def test_load_unload_endpoints_and_metrics(tmp_path):
    engine = _dyn_engine(slots=2)
    blob = tmp_path / "sql.lora"
    blob.write_bytes(encode_adapter(_weights(engine, 77)))
    app = build_app(AsyncEngine(engine), ByteTokenizer(), "tiny-lora", 128)
    client = TestClient(TestServer(app))
    await client.start_server()
    try:
        # Load from a framed file source.
        r = await client.post(
            "/v1/load_lora_adapter",
            json={"lora_name": "sql-adapter", "lora_path": str(blob)},
        )
        assert r.status == 200, await r.text()
        # The dynamic registry drives /v1/models and completions.
        models = await (await client.get("/v1/models")).json()
        assert "sql-adapter" in {m["id"] for m in models["data"]}
        r = await client.post(
            "/v1/completions",
            json={"model": "sql-adapter", "prompt": "hello", "max_tokens": 4},
        )
        assert r.status == 200
        r = await client.post(
            "/v1/completions",
            json={"model": "sql-typo", "prompt": "x", "max_tokens": 2},
        )
        assert r.status == 404
        # Metrics: the dynamic registry + residency ride the labels.
        text = await (await client.get("/metrics")).text()
        assert 'available_lora_adapters="sql-adapter"' in text
        assert 'resident_lora_adapters="sql-adapter"' in text
        assert "llmd:lora_pool_resident_adapters" in text
        assert "llmd:lora_cold_loads_total" in text
        # Duplicate load is a client error (vLLM contract).
        r = await client.post(
            "/v1/load_lora_adapter",
            json={"lora_name": "sql-adapter", "lora_path": str(blob)},
        )
        assert r.status == 400
        # A bad source is a counted 4xx, never a wedged batch.
        r = await client.post(
            "/v1/load_lora_adapter",
            json={"lora_name": "ghost", "lora_path": str(tmp_path / "no")},
        )
        assert r.status == 400
        text = await (await client.get("/metrics")).text()
        assert "llmd:lora_load_failures_total" in text
        assert engine.stats.lora_load_failures_total == 1
        # Unload; unknown unload 404s.
        r = await client.post(
            "/v1/unload_lora_adapter", json={"lora_name": "sql-adapter"}
        )
        assert r.status == 200
        r = await client.post(
            "/v1/unload_lora_adapter", json={"lora_name": "sql-adapter"}
        )
        assert r.status == 404
        # Invalid names never reach the registry (label safety).
        r = await client.post(
            "/v1/load_lora_adapter",
            json={"lora_name": 'bad"name', "lora_path": str(blob)},
        )
        assert r.status == 400
    finally:
        await client.close()


async def test_load_endpoint_disabled_without_pool():
    engine = LLMEngine(EngineConfig(
        model=tiny_model_config(name="tiny"),
        cache=CacheConfig(page_size=4, num_blocks=64, dtype="float32"),
        scheduler=SchedulerConfig(max_num_seqs=2, max_num_batched_tokens=64),
    ))
    app = build_app(AsyncEngine(engine), ByteTokenizer(), "tiny", 128)
    client = TestClient(TestServer(app))
    await client.start_server()
    try:
        r = await client.post(
            "/v1/load_lora_adapter",
            json={"lora_name": "x", "lora_path": "/nope"},
        )
        assert r.status == 400
        assert "disabled" in (await r.json())["error"]["message"]
    finally:
        await client.close()


# --------------------------------------------------------------------- #
# EPP: tri-state residency scoring


def _pod(addr, resident=(), available=()):
    from llmd_tpu.epp.types import Endpoint

    ep = Endpoint(address=addr)
    ep.attrs["ResidentAdapters"] = list(resident)
    ep.attrs["AvailableAdapters"] = list(available)
    return ep


def test_lora_affinity_scorer_tri_state(monkeypatch):
    from llmd_tpu.epp.scorers import LoraAffinityScorer
    from llmd_tpu.epp.types import LLMRequest

    req = LLMRequest(request_id="r1", model="ad1", body={"model": "ad1"})
    pods = [
        _pod("resident:1", resident=["ad1"], available=["ad1"]),
        _pod("registered:1", resident=["other"], available=["ad1", "other"]),
        _pod("cold:1", resident=[], available=["other"]),
    ]
    scores = LoraAffinityScorer().score(req, pods)
    assert scores["resident:1"] == 1.0
    assert scores["registered:1"] == 0.5
    assert scores["cold:1"] == 0.0
    # Weights: defaults < env < scorer parameters.
    monkeypatch.setenv("LLMD_LORA_TIER_WEIGHTS", "registered=0.7")
    assert LoraAffinityScorer().score(req, pods)["registered:1"] == 0.7
    s = LoraAffinityScorer(tier_weights={"registered": 0.25})
    assert s.score(req, pods)["registered:1"] == 0.25


def test_lora_affinity_scorer_legacy_fallback():
    """Engines predating the resident label: LoadedAdapters (the
    running/waiting scrape) stands in for residency."""
    from llmd_tpu.epp.scorers import LoraAffinityScorer
    from llmd_tpu.epp.types import Endpoint, LLMRequest

    ep = Endpoint(address="old:1")
    ep.attrs["LoadedAdapters"] = ["ad1"]
    req = LLMRequest(request_id="r1", model="ad1", body={"model": "ad1"})
    assert LoraAffinityScorer().score(req, [ep])["old:1"] == 1.0


def test_extract_attrs_resident_label():
    from llmd_tpu.epp.datalayer import extract_attrs

    attrs = extract_attrs(
        'vllm:lora_requests_info{max_lora="4",'
        'running_lora_adapters="a",waiting_lora_adapters="",'
        'available_lora_adapters="a, b, c",'
        'resident_lora_adapters="a, b",model_name="m"} 1\n'
    )
    assert attrs["ResidentAdapters"] == ["a", "b"]
    assert attrs["AvailableAdapters"] == ["a", "b", "c"]
    assert attrs["LoadedAdapters"] == ["a"]


# --------------------------------------------------------------------- #
# fleetsim scenario surface (full gates run in the CI soak matrix)


def test_lora_tenant_scenario_small_scale():
    from llmd_tpu.fleetsim.scenarios import build_lora_tenant
    from llmd_tpu.fleetsim.scoreboard import to_canonical_json

    aff = build_lora_tenant(0, 0.25, affinity=True).run()
    assert aff["ok"], aff["invariants"]
    lo = aff["lora"]
    assert lo["cold_loads"] >= 1 and lo["evictions"] >= 1
    assert lo["pinned_evictions"] == 0
    blind = build_lora_tenant(0, 0.25, affinity=False).run()
    assert blind["ok"], blind["invariants"]
    # THE scenario gate: residency-affinity routing strictly beats
    # adapter-blind routing on resident-hit ratio (exact virtual time).
    assert lo["hit_ratio"] > blind["lora"]["hit_ratio"]
    # Byte determinism (the CI soak matrix re-asserts across processes).
    again = build_lora_tenant(0, 0.25, affinity=True).run()
    assert to_canonical_json(again) == to_canonical_json(aff)
