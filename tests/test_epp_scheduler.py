"""EPP pipeline unit tests: scorers, filters, pickers, profiles, flow control.

Covers the reference scheduler semantics (scheduling.md:44-118) and
flow-control dispatch tiers (flow-control.md:197-254) without HTTP.
"""

import asyncio
import json

import pytest

from llmd_tpu.epp.config import DEFAULT_CONFIG, PD_CONFIG, build_scheduler
from llmd_tpu.epp.flow_control import (
    BandConfig,
    FlowControl,
    Outcome,
    SaturationDetector,
)
from llmd_tpu.epp.plugins import SchedulingProfile, create_plugin
from llmd_tpu.epp.prefix_approx import ApproxPrefixIndex
from llmd_tpu.epp.types import (
    KV_CACHE_USAGE,
    ROLE_LABEL,
    WAITING_QUEUE_SIZE,
    Endpoint,
    LLMRequest,
)

pytestmark = pytest.mark.anyio


@pytest.fixture
def anyio_backend():
    return "asyncio"


def mk_pods(n=3, **attrs):
    return [Endpoint(address=f"10.0.0.{i}:8000", attrs=dict(attrs)) for i in range(n)]


def mk_req(prompt="hello world " * 50, **kw):
    return LLMRequest(request_id="r1", prompt_text=prompt, **kw)


def test_queue_scorer_prefers_empty_queue():
    pods = mk_pods(3)
    pods[0].attrs[WAITING_QUEUE_SIZE] = 10
    pods[1].attrs[WAITING_QUEUE_SIZE] = 0
    pods[2].attrs[WAITING_QUEUE_SIZE] = 5
    s = create_plugin("queue-scorer")
    scores = s.score(mk_req(), pods)
    assert scores[pods[1].address] == 1.0
    assert scores[pods[0].address] == 0.0


def test_kv_scorer():
    pods = mk_pods(2)
    pods[0].attrs[KV_CACHE_USAGE] = 0.9
    pods[1].attrs[KV_CACHE_USAGE] = 0.1
    s = create_plugin("kv-cache-utilization-scorer")
    scores = s.score(mk_req(), pods)
    assert scores[pods[1].address] > scores[pods[0].address]


def test_role_filters():
    pods = mk_pods(3)
    pods[0].labels[ROLE_LABEL] = "prefill"
    pods[1].labels[ROLE_LABEL] = "decode"
    # pods[2] defaults to prefill-decode
    prefill = create_plugin("prefill-filter").filter(mk_req(), pods)
    decode = create_plugin("decode-filter").filter(mk_req(), pods)
    assert {p.address for p in prefill} == {pods[0].address, pods[2].address}
    assert {p.address for p in decode} == {pods[1].address, pods[2].address}


def test_prefix_index_longest_consecutive():
    idx = ApproxPrefixIndex(block_chars=4)
    h = idx.hashes("aaaabbbbcccc")
    idx.record_routed(h[:2], "podA")  # A holds blocks 0-1
    idx.record_routed(h, "podB")  # B holds all 3
    matches = idx.match_lengths(h)
    assert matches["podA"] == 2
    assert matches["podB"] == 3
    # different text shares no blocks
    assert idx.match_lengths(idx.hashes("zzzzyyyyxxxx")) == {}


def test_prefix_scorer_affinity_via_profile():
    sched = build_scheduler(DEFAULT_CONFIG)
    pods = mk_pods(3)
    prompt = "the quick brown fox " * 100
    r1 = mk_req(prompt)
    res1 = sched.schedule(r1, pods)
    # Second identical prompt must land on the same pod (prefix affinity
    # dominates with weight 3).
    r2 = mk_req(prompt)
    res2 = sched.schedule(r2, pods)
    assert res2.primary.address == res1.primary.address


def test_no_hit_lru_spreads_cold_prompts():
    sched = build_scheduler(DEFAULT_CONFIG)
    pods = mk_pods(3)
    seen = set()
    for i in range(3):
        res = sched.schedule(mk_req(f"completely different prompt {i} " * 60), pods)
        seen.add(res.primary.address)
    assert len(seen) == 3, "cold prompts should spread across the pool"


def test_disagg_handler_long_prompt_gets_prefill():
    sched = build_scheduler(PD_CONFIG)
    pods = mk_pods(4)
    pods[0].labels[ROLE_LABEL] = "prefill"
    pods[1].labels[ROLE_LABEL] = "prefill"
    pods[2].labels[ROLE_LABEL] = "decode"
    pods[3].labels[ROLE_LABEL] = "decode"
    long_req = mk_req("x" * 8192)  # ~2048 approx tokens > 256 threshold
    res = sched.schedule(long_req, pods)
    assert res.primary.labels[ROLE_LABEL] == "decode"
    assert res.prefill is not None
    assert res.prefill.labels[ROLE_LABEL] == "prefill"
    short_req = mk_req("short")
    res = sched.schedule(short_req, pods)
    assert res.prefill is None, "short prompts stay decode-only"


def test_disagg_decider_skips_prefill_when_cached():
    sched = build_scheduler(PD_CONFIG)
    pods = mk_pods(2)
    pods[0].labels[ROLE_LABEL] = "prefill"
    pods[1].labels[ROLE_LABEL] = "decode"
    prompt = "y" * 8192
    first = sched.schedule(mk_req(prompt), pods)
    assert first.prefill is not None, "cold long prompt should disaggregate"
    # Same prompt again: its prefix is now indexed on the decode pod, so the
    # decider must keep it decode-only (disaggregation/README.md:57-99).
    again = sched.schedule(mk_req(prompt), pods)
    assert again.primary.address == first.primary.address
    assert again.prefill is None, "cached prompt must not be disaggregated"


def test_topology_affinity_pairs_prefill_with_decode_slice():
    """North-star deliverable: P->D pairing prefers the decode pod's
    slice (KV over ICI) and, above that, its host. The decode profile
    runs first; its pick anchors the prefill profile's topology scorer."""
    sched = build_scheduler(PD_CONFIG)
    pods = mk_pods(5)
    # decode pods on slice-a; prefill candidates across slices
    pods[0].labels.update({ROLE_LABEL: "decode", "llm-d.ai/slice": "a",
                           "llm-d.ai/node": "a-host0"})
    pods[1].labels.update({ROLE_LABEL: "prefill", "llm-d.ai/slice": "b",
                           "llm-d.ai/node": "b-host0"})
    pods[2].labels.update({ROLE_LABEL: "prefill", "llm-d.ai/slice": "a",
                           "llm-d.ai/node": "a-host1"})
    pods[3].labels.update({ROLE_LABEL: "prefill", "llm-d.ai/slice": "c",
                           "llm-d.ai/node": "c-host0"})
    pods[4].labels.update({ROLE_LABEL: "prefill", "llm-d.ai/slice": "a",
                           "llm-d.ai/node": "a-host0"})  # same HOST as decode
    res = sched.schedule(mk_req("z" * 8192), pods)
    assert res.primary is pods[0]
    # same-host prefill wins over same-slice; off-slice never picked
    assert res.prefill is pods[4]

    # without the same-host candidate, same-slice wins
    pods2 = [pods[0], pods[1], pods[2], pods[3]]
    res = sched.schedule(mk_req("w" * 8192), pods2)
    assert res.prefill is pods[2]


def test_responses_structured_input_parsing():
    from llmd_tpu.epp.handler import openai_parse

    body = json.dumps(
        {"input": [{"role": "user", "content": "k" * 800}], "model": "m"}
    ).encode()
    req = openai_parse("/v1/responses", {}, body)
    assert req.approx_prompt_tokens > 100, "structured input must count"


def test_scheduler_empty_pool_raises():
    from llmd_tpu.epp.scheduler import NoEndpointsError

    sched = build_scheduler(DEFAULT_CONFIG)
    with pytest.raises(NoEndpointsError):
        sched.schedule(mk_req(), [])


def test_prefix_cache_affinity_filter_sticky_and_gates():
    """Epsilon-greedy sticky filter (scheduling.md:77-80): narrows to the
    endpoints holding the prompt's prefix; epsilon explores; the TTFT
    load gate breaks stickiness when sticky pods run significantly slow."""
    filt = create_plugin(
        "prefix-cache-affinity-filter", epsilon=0.0, seed=0,
        sticky_threshold=0.5,
    )
    pods = mk_pods(3)
    prompt = "conversation history " * 100

    # Cold: no index entries -> no narrowing.
    req = mk_req(prompt)
    assert filt.filter(req, pods) == pods
    filt.on_routed(req, pods[1])  # the pick lands on pod 1

    # Warm: the same prompt now narrows to the sticky pod.
    req2 = mk_req(prompt + " next turn")
    kept = filt.filter(req2, pods)
    assert kept == [pods[1]]

    # TTFT load gate: sticky pod significantly slower -> full pool again.
    pods[1].attrs["LastTTFT"] = 2.0
    pods[0].attrs["LastTTFT"] = 0.1
    pods[2].attrs["LastTTFT"] = 0.1
    req3 = mk_req(prompt + " another turn")
    assert filt.filter(req3, pods) == pods

    # Epsilon = 1.0 always explores even when sticky is healthy.
    always_explore = create_plugin(
        "prefix-cache-affinity-filter", epsilon=1.0, seed=0,
    )
    req4 = mk_req(prompt)
    always_explore.filter(req4, pods)
    always_explore.on_routed(req4, pods[0])
    assert always_explore.filter(mk_req(prompt), pods) == pods


def test_weighted_random_picker_distribution():
    picker = create_plugin("weighted-random-picker", seed=0)
    pods = mk_pods(2)
    scored = {pods[0].address: 0.9, pods[1].address: 0.1}
    wins = sum(
        1 for _ in range(200) if picker.pick(mk_req(), scored, pods) is pods[0]
    )
    assert wins > 140  # ~180 expected


# --------------------------------------------------------------------- #
# flow control


async def test_flow_dispatch_and_priority():
    fc = FlowControl(
        bands=[BandConfig(priority=0), BandConfig(priority=10)],
        saturation=SaturationDetector(max_inflight=1),
    )
    fc.start()
    order = []

    async def run(req):
        out = await fc.enqueue_and_wait(req)
        order.append(req.request_id)
        return out

    # Occupy the single slot.
    first = asyncio.create_task(run(LLMRequest(request_id="warm", priority=0)))
    await asyncio.sleep(0.05)
    # Two queued: low priority first-in, high priority second-in.
    low = asyncio.create_task(run(LLMRequest(request_id="low", priority=0)))
    high = asyncio.create_task(run(LLMRequest(request_id="high", priority=10)))
    await asyncio.sleep(0.05)
    fc.release()  # free the slot -> dispatcher must pick HIGH first
    await asyncio.sleep(0.05)
    fc.release()
    await asyncio.gather(first, low, high)
    assert order[0] == "warm"
    assert order[1] == "high", f"priority band order violated: {order}"
    await fc.drain()


async def test_flow_capacity_rejection():
    fc = FlowControl(
        bands=[BandConfig(priority=0, max_requests=1)],
        saturation=SaturationDetector(max_inflight=0),  # nothing dispatches
    )
    fc.start()
    t1 = asyncio.create_task(fc.enqueue_and_wait(LLMRequest(request_id="a")))
    await asyncio.sleep(0.02)
    out = await fc.enqueue_and_wait(LLMRequest(request_id="b"))
    assert out is Outcome.REJECTED_CAPACITY
    await fc.drain()
    assert await t1 is Outcome.EVICTED_SHUTDOWN


async def test_flow_ttl_eviction():
    fc = FlowControl(
        bands=[BandConfig(priority=0, ttl_s=0.05)],
        saturation=SaturationDetector(max_inflight=0),
    )
    fc.start()
    out = await fc.enqueue_and_wait(LLMRequest(request_id="x"))
    assert out is Outcome.EVICTED_TTL
    await fc.drain()


async def test_flow_unconfigured_priority_keeps_rank():
    # priority 10 has no configured band but must still beat priority 0.
    fc = FlowControl(saturation=SaturationDetector(max_inflight=1))
    fc.start()
    order = []

    async def run(req):
        await fc.enqueue_and_wait(req)
        order.append(req.request_id)

    warm = asyncio.create_task(run(LLMRequest(request_id="warm")))
    await asyncio.sleep(0.05)
    low = asyncio.create_task(run(LLMRequest(request_id="low", priority=0)))
    high = asyncio.create_task(run(LLMRequest(request_id="high", priority=10)))
    await asyncio.sleep(0.05)
    fc.release()
    await asyncio.sleep(0.05)
    fc.release()
    await asyncio.gather(warm, low, high)
    assert order[1] == "high", order
    await fc.drain()


async def test_flow_edf_no_slo_not_starved():
    """EDF: a no-SLO request gets a FINITE default deadline (arrival +
    DEFAULT_EDF_BUDGET_S) so an SLO-carrying stream cannot starve it —
    once aged, it sorts ahead of fresher SLO requests whose deadlines
    land later."""
    import time as _time

    from llmd_tpu.epp.flow_control import DEFAULT_EDF_BUDGET_S

    fc = FlowControl(
        ordering="edf", saturation=SaturationDetector(max_inflight=1)
    )
    fc.start()
    order = []

    async def run(req):
        await fc.enqueue_and_wait(req)
        order.append(req.request_id)

    now = _time.monotonic()
    warm = asyncio.create_task(run(LLMRequest(request_id="warm")))
    await asyncio.sleep(0.05)
    # Aged no-SLO request: deadline = (now - 25) + 30 = now + 5.
    no_slo = asyncio.create_task(run(LLMRequest(
        request_id="no-slo", arrival_time=now - (DEFAULT_EDF_BUDGET_S - 5),
    )))
    # Fresh SLO-carrying request with a 10 s budget: deadline = now + 10
    # (later than the aged no-SLO's) — must NOT jump the queue.
    slo = asyncio.create_task(run(LLMRequest(
        request_id="slo", arrival_time=now, ttft_slo_ms=10_000,
    )))
    await asyncio.sleep(0.05)
    fc.release()
    await asyncio.sleep(0.05)
    fc.release()
    await asyncio.gather(warm, no_slo, slo)
    assert order == ["warm", "no-slo", "slo"], order
    fc.release()  # free the slot held by the last dispatch
    # ...while a TIGHT SLO still wins over a fresh no-SLO request.
    warm2 = asyncio.create_task(run(LLMRequest(request_id="warm2")))
    await asyncio.sleep(0.05)
    fresh_no_slo = asyncio.create_task(run(LLMRequest(request_id="fresh")))
    tight = asyncio.create_task(run(LLMRequest(
        request_id="tight", ttft_slo_ms=500,
    )))
    await asyncio.sleep(0.05)
    fc.release()
    await asyncio.sleep(0.05)
    fc.release()
    await asyncio.gather(warm2, fresh_no_slo, tight)
    assert order[-2:] == ["tight", "fresh"], order
    fc.release()
    await fc.drain()


async def test_flow_disabled_passthrough():
    fc = FlowControl(enabled=False, saturation=SaturationDetector(max_inflight=0))
    out = await fc.enqueue_and_wait(LLMRequest(request_id="x"))
    assert out is Outcome.DISPATCHED
    fc.release()
    assert fc.saturation.inflight == 0


async def test_flow_round_robin_fairness():
    fc = FlowControl(saturation=SaturationDetector(max_inflight=1))
    fc.start()
    order = []

    async def run(rid, fid):
        await fc.enqueue_and_wait(LLMRequest(request_id=rid, fairness_id=fid))
        order.append(rid)

    warm = asyncio.create_task(run("warm", "z"))
    await asyncio.sleep(0.05)
    tasks = [
        asyncio.create_task(run("a1", "tenant-a")),
        asyncio.create_task(run("a2", "tenant-a")),
        asyncio.create_task(run("b1", "tenant-b")),
    ]
    await asyncio.sleep(0.05)
    for _ in range(3):
        fc.release()
        await asyncio.sleep(0.05)
    await asyncio.gather(warm, *tasks)
    # round-robin: tenant-b's request must not go last
    assert order.index("b1") < order.index("a2"), order
    await fc.drain()
