"""KV transfer layer tests: shipper protocol, leases, and P/D end-to-end.

The P/D invariance test is the core guarantee: a decode engine that pulls
prefill KV from a producer must emit exactly the tokens an aggregated
engine would (cache-seeded remote KV may never change numerics), while
actually hitting the transferred pages.
"""

import threading
import time

import numpy as np
import pytest

from llmd_tpu.config import (
    CacheConfig,
    EngineConfig,
    ParallelConfig,
    SchedulerConfig,
    tiny_model_config,
)
from llmd_tpu.engine import LLMEngine, SamplingParams
from llmd_tpu.kvtransfer import shipper as shipper_mod
from llmd_tpu.kvtransfer.connector import TPUConnector, pack_pages, unpack_pages
from llmd_tpu.kvtransfer.shipper import PullError, ShipperServer


# --------------------------------------------------------------------------- #
# shipper protocol


@pytest.fixture(params=["native", "python"])
def server(request, monkeypatch):
    if request.param == "python":
        from llmd_tpu.kvtransfer import native

        monkeypatch.setattr(native, "load", lambda: None)
    srv = ShipperServer(port=0)
    if request.param == "native" and srv.backend != "native":
        pytest.skip("native kvship unavailable")
    yield srv
    srv.close()


def test_register_pull_free(server):
    data = b"kv-bytes-" * 1000
    server.register("req-1", data, lease_ms=60_000)
    assert server.registered_count == 1
    assert server.registered_bytes == len(data)

    got = shipper_mod.pull("127.0.0.1", server.port, "req-1")
    assert got == data
    # pull is one-sided: entry survives until free-notify
    assert server.registered_count == 1
    assert shipper_mod.free_notify("127.0.0.1", server.port, "req-1")
    assert server.registered_count == 0
    with pytest.raises(PullError):
        shipper_mod.pull("127.0.0.1", server.port, "req-1")


def test_lease_expiry_and_renew(server):
    server.register("short", b"x" * 64, lease_ms=700)
    server.register("renewed", b"y" * 64, lease_ms=700)
    # Consumer heartbeat extends the lease (operations-vllm.md:155-160).
    assert shipper_mod.renew("127.0.0.1", server.port, "renewed", lease_ms=60_000)
    # Reaper cadence is 500ms; give "short" time to expire.
    time.sleep(1.5)
    with pytest.raises(PullError):
        shipper_mod.pull("127.0.0.1", server.port, "short")
    assert server.expired_count >= 1
    assert shipper_mod.pull("127.0.0.1", server.port, "renewed") == b"y" * 64


def test_stat(server):
    server.register("a", b"1234", lease_ms=60_000)
    n, b = shipper_mod.stat("127.0.0.1", server.port)
    assert (n, b) == (1, 4)


def test_python_client_native_server_interop():
    srv = ShipperServer(port=0)
    if srv.backend != "native":
        pytest.skip("native kvship unavailable")
    try:
        srv.register("k", b"payload", lease_ms=60_000)
        st, payload = shipper_mod._py_roundtrip(
            "127.0.0.1", srv.port, shipper_mod.OP_PULL, "k"
        )
        assert st == shipper_mod.ST_OK and payload == b"payload"
    finally:
        srv.close()


def test_pack_unpack_roundtrip():
    pages = np.random.default_rng(0).normal(size=(2, 3, 2, 4, 16)).astype(np.float32)
    out = unpack_pages(pack_pages(pages))
    np.testing.assert_array_equal(out, pages)


# --------------------------------------------------------------------------- #
# P/D end-to-end through two engines


def make_engine(kv_role=None, seed=0, page=4, num_blocks=64, dtype="float32"):
    cfg = EngineConfig(
        model=tiny_model_config(dtype=dtype),
        cache=CacheConfig(page_size=page, num_blocks=num_blocks, dtype=dtype),
        scheduler=SchedulerConfig(max_num_seqs=8, max_num_batched_tokens=64),
        parallel=ParallelConfig(tensor_parallel_size=1),
        seed=seed,
        kv_role=kv_role,
        kv_transfer_port=0,  # ephemeral
        # This module tests the WIRE protocol (both engines share the
        # pytest process); the in-process device fast path is covered by
        # tests/test_pd_e2e.py::test_pd_local_fastpath*.
        kv_local_fastpath=False,
    )
    return LLMEngine(cfg)


PROMPT = [1, 5, 9, 13, 2, 8, 4, 4, 4, 4, 6, 6, 6, 6, 11, 7, 3, 2]  # 18 toks


def _run(eng, prompt, max_tokens, kv_transfer_params=None):
    rid = eng.add_request(
        list(prompt),
        SamplingParams(temperature=0.0, max_tokens=max_tokens),
        kv_transfer_params=kv_transfer_params,
    )
    outs = []
    final = None
    while eng.has_work():
        for out in eng.step():
            if out.request_id == rid:
                outs.extend(out.new_token_ids)
                if out.finished:
                    final = out
    return outs, final


def test_pd_disagg_matches_aggregated():
    ref_tokens, _ = _run(make_engine(), PROMPT, max_tokens=8)

    producer = make_engine(kv_role="kv_producer")
    consumer = make_engine(kv_role="kv_consumer")
    try:
        # Phase 1: prefill with max_tokens=1 + do_remote_decode (the routing
        # sidecar's prefill request, reference disaggregation/README.md:33-46).
        _, pre = _run(
            producer, PROMPT, max_tokens=1,
            kv_transfer_params={"do_remote_decode": True},
        )
        params = pre.kv_transfer_params
        assert params is not None
        assert params["num_full_pages"] == len(PROMPT) // 4
        # Export staging runs on a background thread (the response leaves
        # after prefill compute); wait for every (layer-group, chunk)
        # cell's registration to land (v3 group framing: transfer_keys
        # is the single source of the key scheme).
        from llmd_tpu.kvtransfer.connector import transfer_keys

        n_cells = len(transfer_keys(params))
        deadline = time.time() + 5
        while time.time() < deadline:
            if producer.kv_connector.server.registered_count == n_cells:
                break
            time.sleep(0.02)
        assert producer.kv_connector.server.registered_count == n_cells

        # Phase 2: decode with the captured params injected.
        toks, final = _run(consumer, PROMPT, max_tokens=8, kv_transfer_params=params)
        assert toks == ref_tokens
        # (18-1)//4 = 4 pages come from the transfer; free-notify reclaimed
        # the producer entry.
        assert final.num_cached_tokens == 16
        assert consumer.kv_connector.imported_requests == 1
        assert producer.kv_connector.server.registered_count == 0
    finally:
        producer.kv_connector.close()
        consumer.kv_connector.close()


def test_pd_disagg_bfloat16_cache_transfers():
    """bf16 (the production cache dtype) must export/pull byte-exact:
    ml_dtypes arrays lack the buffer protocol, so the shipper moves a
    uint8 view and the bundle header carries the dtype by name."""
    ref_tokens, _ = _run(make_engine(dtype="bfloat16"), PROMPT, max_tokens=6)
    producer = make_engine(kv_role="kv_producer", dtype="bfloat16")
    consumer = make_engine(kv_role="kv_consumer", dtype="bfloat16")
    try:
        _, pre = _run(
            producer, PROMPT, max_tokens=1,
            kv_transfer_params={"do_remote_decode": True},
        )
        assert pre.kv_transfer_params is not None
        assert producer.kv_connector.exported_requests == 1
        toks, final = _run(
            consumer, PROMPT, max_tokens=6,
            kv_transfer_params=pre.kv_transfer_params,
        )
        assert toks == ref_tokens
        assert consumer.kv_connector.imported_requests == 1
        assert consumer.kv_connector.import_failures == 0
    finally:
        producer.kv_connector.close()
        consumer.kv_connector.close()


def test_pd_multi_chunk_pipeline_matches_aggregated():
    """A prompt spanning several transfer chunks (the pipelined export
    path: background staging, per-chunk keys, device-side scatters) must
    reproduce the aggregated engine exactly, including the padded tail
    chunk."""
    prompt = list(range(1, 45))  # 44 tokens, page=4 -> 11 full pages
    ref_tokens, _ = _run(make_engine(), prompt, max_tokens=6)

    producer = make_engine(kv_role="kv_producer")
    consumer = make_engine(kv_role="kv_consumer")
    try:
        _, pre = _run(
            producer, prompt, max_tokens=1,
            kv_transfer_params={"do_remote_decode": True},
        )
        params = pre.kv_transfer_params
        assert params["num_full_pages"] == 11
        assert params["num_chunks"] == 2  # 11 pages / 8 per chunk
        assert params["chunk_pages"] == 8
        toks, final = _run(
            consumer, prompt, max_tokens=6, kv_transfer_params=params
        )
        assert toks == ref_tokens
        assert consumer.kv_connector.imported_requests == 1
        assert consumer.kv_connector.import_failures == 0
        # free-notify covered every chunk key
        deadline = time.time() + 5
        while time.time() < deadline:
            if producer.kv_connector.server.registered_count == 0:
                break
            time.sleep(0.02)
        assert producer.kv_connector.server.registered_count == 0
    finally:
        producer.kv_connector.close()
        consumer.kv_connector.close()


def test_pull_wait_blocks_until_registered(server):
    """pull_wait absorbs producer staging lag: the key appears mid-wait."""
    import threading

    from llmd_tpu.kvtransfer import shipper as shipper_mod

    def late_register():
        time.sleep(0.15)
        server.register("late", b"chunk-bytes", 5_000)

    threading.Thread(target=late_register, daemon=True).start()
    t0 = time.monotonic()
    blob = shipper_mod.pull_wait(
        "127.0.0.1", server.port, "late", deadline=time.monotonic() + 5
    )
    assert blob == b"chunk-bytes"
    assert time.monotonic() - t0 >= 0.1
    # hard timeout on a key that never appears
    with pytest.raises(shipper_mod.PullError):
        shipper_mod.pull_wait(
            "127.0.0.1", server.port, "never", deadline=time.monotonic() + 0.2
        )


def test_producer_crash_mid_pull_recompute():
    """Producer dies BETWEEN chunk pulls (crash-mid-transfer seam): the
    consumer's load-failure policy degrades to local recompute and the
    output still matches the aggregated engine."""
    prompt = list(range(1, 45))  # 11 full pages -> 2 chunks
    ref_tokens, _ = _run(make_engine(), prompt, max_tokens=5)
    producer = make_engine(kv_role="kv_producer")
    consumer = make_engine(kv_role="kv_consumer")
    try:
        _, pre = _run(
            producer, prompt, max_tokens=1,
            kv_transfer_params={"do_remote_decode": True},
        )
        params = pre.kv_transfer_params
        assert params["num_chunks"] == 2
        # let staging finish, then crash the producer after the consumer's
        # FIRST chunk pull
        deadline = time.time() + 5
        while time.time() < deadline and (
            producer.kv_connector.server.registered_count < 2
        ):
            time.sleep(0.02)
        orig_pull_wait = shipper_mod.pull_wait
        calls = {"n": 0}

        def crashing_pull_wait(host, port, key, deadline, poll_s=0.01):
            blob = orig_pull_wait(host, port, key, deadline, poll_s)
            calls["n"] += 1
            if calls["n"] == 1:
                producer.kv_connector.server.close()  # crash mid-transfer
            return blob

        shipper_mod.pull_wait = crashing_pull_wait
        try:
            toks, _ = _run(
                consumer, prompt, max_tokens=5, kv_transfer_params=params
            )
        finally:
            shipper_mod.pull_wait = orig_pull_wait
        assert toks == ref_tokens  # recomputed locally, numerics intact
        assert consumer.kv_connector.import_failures == 1
        assert consumer.kv_connector.imported_requests == 0
    finally:
        producer.kv_connector.close()
        consumer.kv_connector.close()


def test_producer_crash_fail_policy_raises():
    """Same seam with kv_load_failure_policy='fail' (the reference's
    recommended strict mode, operations-vllm.md:118-139): the import
    surfaces KVLoadError instead of silently recomputing."""
    from llmd_tpu.kvtransfer.connector import KVLoadError

    producer = make_engine(kv_role="kv_producer")
    consumer = make_engine(kv_role="kv_consumer")
    consumer.kv_connector.cfg.load_failure_policy = "fail"
    consumer.kv_connector.cfg.lease_ms = 500  # short pull-wait deadline
    try:
        _, pre = _run(
            producer, PROMPT, max_tokens=1,
            kv_transfer_params={"do_remote_decode": True},
        )
        params = pre.kv_transfer_params
        producer.kv_connector.server.close()  # crash before any pull
        with pytest.raises(KVLoadError):
            consumer.kv_connector.import_for_prompt(list(PROMPT), params)
    finally:
        producer.kv_connector.close()
        consumer.kv_connector.close()


def test_lease_expiry_reclaims_export_and_consumer_recomputes():
    """An export whose lease expires (decode never arrived / heartbeat
    died) is reaped; a late consumer degrades to recompute with exact
    numerics."""
    ref_tokens, _ = _run(make_engine(), PROMPT, max_tokens=4)
    producer = make_engine(kv_role="kv_producer")
    producer.kv_connector.cfg.lease_ms = 200
    consumer = make_engine(kv_role="kv_consumer")
    consumer.kv_connector.cfg.lease_ms = 500  # short pull-wait deadline
    try:
        _, pre = _run(
            producer, PROMPT, max_tokens=1,
            kv_transfer_params={"do_remote_decode": True},
        )
        deadline = time.time() + 5
        while time.time() < deadline and (
            producer.kv_connector.server.registered_count == 0
        ):
            time.sleep(0.02)
        # expire: the reaper reclaims the entry
        deadline = time.time() + 5
        while time.time() < deadline and (
            producer.kv_connector.server.registered_count > 0
        ):
            time.sleep(0.05)
        assert producer.kv_connector.server.registered_count == 0
        assert producer.kv_connector.server.expired_count >= 1
        toks, _ = _run(
            consumer, PROMPT, max_tokens=4,
            kv_transfer_params=pre.kv_transfer_params,
        )
        assert toks == ref_tokens
        assert consumer.kv_connector.import_failures == 1
    finally:
        producer.kv_connector.close()
        consumer.kv_connector.close()


def test_lease_renewal_keeps_chunked_export_alive():
    """The sidecar-heartbeat seam at the wire level: renewing EVERY chunk
    key (transfer_keys) holds a queued transfer past several base leases;
    the pull then still succeeds."""
    from llmd_tpu.kvtransfer.connector import transfer_keys

    producer = make_engine(kv_role="kv_producer")
    producer.kv_connector.cfg.lease_ms = 300
    consumer = make_engine(kv_role="kv_consumer")
    try:
        prompt = list(range(1, 45))  # 2 chunks
        _, pre = _run(
            producer, prompt, max_tokens=1,
            kv_transfer_params={"do_remote_decode": True},
        )
        params = pre.kv_transfer_params
        host, port = params["remote_host"], int(params["remote_port"])
        # v3 group framing: one shipper entry per (layer-group, chunk)
        # cell — transfer_keys is the single source of the key scheme.
        n_cells = len(transfer_keys(params))
        deadline = time.time() + 5
        while time.time() < deadline and (
            producer.kv_connector.server.registered_count < n_cells
        ):
            time.sleep(0.02)
        # hold for 4 base leases, renewing at ~1/3 lease cadence; EVERY
        # chunk key must be renewed each cycle (a short-circuiting any()
        # over a generator would let later chunks expire — the sidecar
        # heartbeat bug class)
        for _ in range(12):
            time.sleep(0.1)
            renewed = [
                shipper_mod.renew(host, port, k, lease_ms=300)
                for k in transfer_keys(params)
            ]
            assert all(renewed), renewed
        assert producer.kv_connector.server.registered_count == n_cells
        n = consumer.kv_connector.import_for_prompt(prompt, params)
        assert n == 11  # every transferred page adopted
        assert consumer.kv_connector.import_failures == 0
    finally:
        producer.kv_connector.close()
        consumer.kv_connector.close()


def test_pd_consumer_recompute_fallback():
    consumer = make_engine(kv_role="kv_consumer")
    try:
        # Bogus remote: pull fails, policy=recompute => local prefill.
        toks, final = _run(
            consumer, PROMPT, max_tokens=4,
            kv_transfer_params={
                "remote_host": "127.0.0.1", "remote_port": 1,
                "remote_key": "nope", "num_full_pages": 4, "page_size": 4,
            },
        )
        assert len(toks) == 4
        assert consumer.kv_connector.import_failures == 1
    finally:
        consumer.kv_connector.close()


def test_q8_wire_roundtrip():
    """int8q wire form: header carries 'int8q:<orig>', scales ride the
    header blob, payload decodes to the exact quantized values."""
    from llmd_tpu.kvtransfer.connector import (
        pack_header_q8, unpack_pages_any,
    )

    rng = np.random.default_rng(3)
    pages = rng.standard_normal((2, 3, 2, 4, 8)).astype(np.float32)
    halves = pages.reshape(2, 3, 2, 4, 2, 4)
    amax = np.abs(halves).max(axis=-1, keepdims=True)
    scale = np.maximum(amax, 1e-30) / 127.0
    q8 = np.clip(np.round(halves / scale), -127, 127).astype(np.int8)
    q8 = q8.reshape(2, 3, 2, 4, 8)
    scales = scale[..., 0].astype(np.float16)  # [..., 2] K/V half scales
    blob = pack_header_q8(q8, "float32") + scales.tobytes() + q8.tobytes()
    kind, got_q8, got_scales, orig = unpack_pages_any(blob)
    assert kind == "q8" and orig == "float32"
    np.testing.assert_array_equal(got_q8, q8)
    np.testing.assert_array_equal(got_scales, scales)
    # exact form still decodes through the same entry point
    from llmd_tpu.kvtransfer.connector import pack_pages

    kind, got = unpack_pages_any(pack_pages(pages))
    assert kind == "exact"
    np.testing.assert_array_equal(got, pages)


def test_pd_int8_transfer_end_to_end():
    """kv_transfer_dtype='int8': the transfer moves half the bytes and the
    consumer's imported pages match the producer's within the per-row
    quantization error; generation completes via the cache-seeded path."""
    from llmd_tpu.config import EngineConfig

    prompt = list(range(1, 45))  # 11 full pages -> 2 chunks

    def mk(role, dtype_):
        cfg = EngineConfig(
            model=tiny_model_config(dtype="float32"),
            cache=CacheConfig(page_size=4, num_blocks=64, dtype="float32"),
            scheduler=SchedulerConfig(max_num_seqs=8, max_num_batched_tokens=64),
            kv_role=role,
            kv_transfer_port=0,
            kv_transfer_dtype=dtype_,
            kv_local_fastpath=False,
        )
        return LLMEngine(cfg)

    producer = mk("kv_producer", "int8")
    consumer = mk("kv_consumer", "auto")  # producer-driven encoding
    try:
        _, pre = _run(
            producer, prompt, max_tokens=1,
            kv_transfer_params={"do_remote_decode": True},
        )
        params = pre.kv_transfer_params
        toks, final = _run(
            consumer, prompt, max_tokens=5, kv_transfer_params=params
        )
        assert len(toks) == 5
        assert consumer.kv_connector.imported_requests == 1
        assert consumer.kv_connector.import_failures == 0
        # 10 of 11 transferred pages hit (the last page keeps >= 1 token
        # to compute for the first logits)
        assert final.num_cached_tokens == 40
        # wire bytes well under half the exact f32 encoding (int8 payload
        # + f16 row scales vs 4-byte elements)
        cfgm = tiny_model_config()
        rows = cfgm.num_layers * 16 * cfgm.num_kv_heads * 4  # 2 chunks x 8 pages
        exact = rows * 2 * cfgm.head_dim * 4
        assert consumer.kv_connector.imported_bytes < exact * 0.6
    finally:
        producer.kv_connector.close()
        consumer.kv_connector.close()


def test_pd_int8_transfer_page_accuracy():
    """Direct accuracy check: export with int8 encoding, fetch the bundle,
    and compare the dequantized pages to the producer's exact pages."""
    producer = make_engine(kv_role="kv_producer")
    producer.kv_connector.cfg.transfer_dtype = "int8"
    # Monolithic v2 wire: this test inspects the fetched bundle's HOST
    # view directly, which a group-streamed fetch never materializes
    # (cells scatter straight into pool pages). Grouped int8 accuracy
    # is covered by the streamed-parity tests in test_kv_stream.py.
    producer.kv_connector.cfg.stream_groups = 1
    consumer = make_engine(kv_role="kv_consumer")
    try:
        prompt = list(range(1, 30))  # 7 full pages
        rid = producer.add_request(
            list(prompt),
            SamplingParams(temperature=0.0, max_tokens=1),
            kv_transfer_params={"do_remote_decode": True},
        )
        final = None
        block_ids = None
        orig_hook = producer.scheduler.finish_hook

        def capture_hook(req):
            nonlocal block_ids
            block_ids = list(req.block_ids)
            orig_hook(req)

        producer.scheduler.finish_hook = capture_hook
        while producer.has_work():
            for out in producer.step():
                if out.finished:
                    final = out
        params = final.kv_transfer_params
        exact = producer.kv_connector.runner.gather_pages(block_ids[:7])
        bundle = consumer.kv_connector.fetch_remote(list(prompt), params)
        got = bundle.host_pages(7)
        rel = np.linalg.norm(
            got.astype(np.float32) - exact.astype(np.float32)
        ) / np.linalg.norm(exact.astype(np.float32))
        assert rel < 0.01, rel
        # each K/V half must be accurate INDEPENDENTLY (separate scales:
        # a large K half must not crush the V half's resolution)
        D = exact.shape[-1] // 2
        for half in (slice(0, D), slice(D, None)):
            e = exact[..., half].astype(np.float32)
            g = got[..., half].astype(np.float32)
            rel_h = np.linalg.norm(g - e) / max(np.linalg.norm(e), 1e-9)
            assert rel_h < 0.01, rel_h
    finally:
        producer.kv_connector.close()
        consumer.kv_connector.close()


def test_pd_int8_transfer_rejects_mla():
    """MLA latent rows don't fit the K|V half-split scale layout: int8
    transfer must refuse at startup, not silently degrade accuracy."""
    from llmd_tpu.config import EngineConfig

    with pytest.raises(ValueError, match="MLA"):
        LLMEngine(EngineConfig(
            model=tiny_model_config(
                kv_lora_rank=32, q_lora_rank=0, qk_nope_head_dim=16,
                qk_rope_head_dim=8, v_head_dim=16,
            ),
            cache=CacheConfig(page_size=4, num_blocks=32, dtype="float32"),
            scheduler=SchedulerConfig(max_num_seqs=2, max_num_batched_tokens=32),
            kv_role="kv_producer",
            kv_transfer_port=0,
            kv_transfer_dtype="int8",
        ))


def test_adaptive_encoding_decision_logic():
    """transfer_dtype='adaptive': the picker alternates while cold,
    converges to the measured-faster encoding, and re-probes the loser
    periodically so a drifting link can flip the choice."""
    conn = TPUConnector.__new__(TPUConnector)
    conn._local_lock = threading.Lock()  # pick/observe run under it
    conn._enc_rate = {"exact": None, "q8": None}
    conn._adaptive_exports = 0

    # Cold: alternates so both forms get measured.
    picks = [conn._adaptive_pick_q8() for _ in range(4)]
    assert True in picks and False in picks

    # Link where the exact form stages faster per ORIGINAL byte
    # (q8's quantize overhead dominates the byte saving).
    conn._observe_encoding(False, 100 << 20, 1.0)  # exact: 100 MB/s
    conn._observe_encoding(True, 100 << 20, 2.0)   # q8:     50 MB/s
    conn._adaptive_exports = 0
    picks = [conn._adaptive_pick_q8() for _ in range(7)]
    assert picks.count(False) == 7  # exact wins every non-probe turn
    assert conn._adaptive_pick_q8() is True  # 8th = re-probe the loser

    # Slow link: halved bytes dominate -> q8 flips to winner. EWMA must
    # actually move on repeated observations.
    for _ in range(12):
        conn._observe_encoding(False, 10 << 20, 4.0)  # exact: 2.5 MB/s
        conn._observe_encoding(True, 10 << 20, 1.0)   # q8:   10 MB/s
    conn._adaptive_exports = 0
    assert all(conn._adaptive_pick_q8() for _ in range(7))


def test_pd_adaptive_transfer_end_to_end():
    """transfer_dtype='adaptive' serves transfers correctly from the
    first (cold, alternating) exports on, and learns per-encoding
    staging rates as it goes."""
    from llmd_tpu.config import EngineConfig

    prompt = list(range(1, 45))

    def mk(role, dtype_):
        cfg = EngineConfig(
            model=tiny_model_config(dtype="float32"),
            cache=CacheConfig(page_size=4, num_blocks=64, dtype="float32"),
            scheduler=SchedulerConfig(max_num_seqs=8, max_num_batched_tokens=64),
            kv_role=role,
            kv_transfer_port=0,
            kv_transfer_dtype=dtype_,
            kv_local_fastpath=False,
        )
        return LLMEngine(cfg)

    producer = mk("kv_producer", "adaptive")
    consumer = mk("kv_consumer", "auto")
    try:
        for i in range(3):  # both encodings get exercised while cold
            p = [t + i for t in prompt]
            _, pre = _run(
                producer, p, max_tokens=1,
                kv_transfer_params={"do_remote_decode": True},
            )
            toks, final = _run(
                consumer, p, max_tokens=4,
                kv_transfer_params=pre.kv_transfer_params,
            )
            assert len(toks) == 4
        assert consumer.kv_connector.imported_requests == 3
        assert consumer.kv_connector.import_failures == 0
        st = producer.kv_connector.stats()
        assert st["enc_rate_exact_mbps"] > 0
        assert st["enc_rate_q8_mbps"] > 0
    finally:
        producer.kv_connector.close()
        consumer.kv_connector.close()
