"""Single-chip serving benchmark (driver contract).

Prints ONE JSON line: {"metric", "value", "unit", "vs_baseline", "extras"}.

Headline: offline continuous-batching decode of a Llama-3.2-3B-class model
(W8A8 INT8 weights — the TPU counterpart of the serving precision the
reference's headline path uses, FP8 DeepGEMM, docker/Dockerfile.cuda:69-70)
— batch 128, 128-token prompts, 64 output tokens, greedy, end-to-end
through LLMEngine (scheduler + paged KV + sampling), so host overhead
counts. vs_baseline: ratio against the reference's closest per-chip decode
figure, ~1,600 output tok/s per decode GPU (DeepSeek-R1 wide-EP on
32xH200, reference guides/wide-ep-lws/README.md:271; see BASELINE.md).
Different model/chip class — a tracking ratio, not a like-for-like claim.

extras (north-star shapes, BASELINE.json):
  dense_bf16_tok_s — same workload, bf16 weights (r01/r02 headline basis;
                    keeps the precision-for-speed trade visible).
  mla_moe_tok_s   — decode tok/s on a DeepSeek-V2-Lite-geometry MLA+MoE
                    model (depth cut to 8 to fit one chip's HBM), INT8
                    grouped-GEMM expert backend (the reference's FP8
                    DeepGEMM role). The architecture the 2.2k tok/s/chip
                    north star names.
  pd_ttft_p50_ms  — p50 time-to-first-token through the FULL P/D path
                    (client -> sidecar -> prefill engine -> kvship KV
                    transfer -> decode engine first token) on localhost,
                    against the < 200 ms north-star target.
  dispatch_rtt_ms — measured host->device dispatch round-trip. Under the
                    axon tunnel this is ~100 ms (vs sub-ms co-located),
                    and the P/D path pays several dispatches plus two
                    ~25 MB HBM<->host stagings, so pd_ttft_p50_ms has an
                    environment floor far above the target; read it
                    relative to this RTT.
"""

from __future__ import annotations

import asyncio
import json
import time

REFERENCE_PER_CHIP_TOKS = 1600.0  # wide-ep-lws/README.md:271


def bench_dense(quantization: str | None = "int8"):
    import numpy as np

    from llmd_tpu.config import (
        CacheConfig, EngineConfig, ParallelConfig, SchedulerConfig,
    )
    from llmd_tpu.engine import LLMEngine, SamplingParams
    from llmd_tpu.models.registry import get_model_config

    B, ISL, OSL = 128, 128, 64
    model = get_model_config(
        "llama-3.2-3b", max_model_len=512, quantization=quantization
    )
    # Tuned for the tunnel-attached single chip: the ~100ms host-dispatch
    # RTT dominates small steps, so the whole prefill rides ONE batched
    # dispatch (B*ISL=16384 tokens) and the whole decode ONE fused
    # 64-step window. Measured ladder (same workload): dw=16/mbt=2048
    # 997 tok/s -> dw=32/4096 1209 -> dw=64/8192 1468 -> dw=64/16384 1777;
    # page sweep: page=32 3244, B=192 3486, B=256 3452 -> stay 128/16.
    cfg = EngineConfig(
        model=model,
        cache=CacheConfig(page_size=16, num_blocks=2048, dtype="bfloat16"),
        scheduler=SchedulerConfig(
            max_num_seqs=B, max_num_batched_tokens=16384, decode_window=64
        ),
        parallel=ParallelConfig(tensor_parallel_size=1),
        seed=0,
    )
    engine = LLMEngine(cfg)
    rng = np.random.default_rng(0)
    sampling = SamplingParams(temperature=0.0, max_tokens=OSL, ignore_eos=True)
    warm = [list(rng.integers(1, model.vocab_size, size=ISL)) for _ in range(B)]
    engine.generate(warm, sampling)

    prompts = [list(rng.integers(1, model.vocab_size, size=ISL)) for _ in range(B)]
    t0 = time.monotonic()
    out = engine.generate(prompts, sampling)
    dt = time.monotonic() - t0
    total_out = sum(len(v) for v in out.values())
    assert total_out == B * OSL, (total_out, B * OSL)
    del engine
    return total_out / dt


def bench_mla_moe():
    """DeepSeek-family decode: MLA latent KV + grouped-GEMM MoE experts."""
    import numpy as np

    from llmd_tpu.config import (
        CacheConfig, EngineConfig, ParallelConfig, SchedulerConfig,
    )
    from llmd_tpu.engine import LLMEngine, SamplingParams
    from llmd_tpu.models.registry import get_model_config

    B, ISL, OSL = 128, 128, 64
    # V2-Lite geometry (MLA rank 512+64, 64 experts top-6, shared expert,
    # dense first layer) at depth 8: ~4B params fit one chip. INT8 experts
    # stream half the bytes through the grouped GEMM — the quantized-
    # serving shape the reference runs this architecture in (FP8 DeepGEMM).
    model = get_model_config(
        "deepseek-v2-lite", num_layers=8, max_model_len=512,
        quantization="int8",
    )
    cfg = EngineConfig(
        model=model,
        cache=CacheConfig(page_size=16, num_blocks=2048, dtype="bfloat16"),
        scheduler=SchedulerConfig(
            max_num_seqs=B, max_num_batched_tokens=16384, decode_window=64
        ),
        parallel=ParallelConfig(tensor_parallel_size=1, moe_backend="grouped"),
        seed=0,
    )
    engine = LLMEngine(cfg)
    rng = np.random.default_rng(1)
    sampling = SamplingParams(temperature=0.0, max_tokens=OSL, ignore_eos=True)
    warm = [list(rng.integers(1, model.vocab_size, size=ISL)) for _ in range(B)]
    engine.generate(warm, sampling)

    prompts = [list(rng.integers(1, model.vocab_size, size=ISL)) for _ in range(B)]
    t0 = time.monotonic()
    out = engine.generate(prompts, sampling)
    dt = time.monotonic() - t0
    total_out = sum(len(v) for v in out.values())
    assert total_out == B * OSL, (total_out, B * OSL)
    del engine
    return total_out / dt


async def _bench_pd_ttft():
    """p50 TTFT through sidecar two-phase P->D with a real KV transfer."""
    import numpy as np
    from aiohttp import ClientSession
    from aiohttp.test_utils import TestServer

    from llmd_tpu.config import (
        CacheConfig, EngineConfig, ParallelConfig, SchedulerConfig,
    )
    from llmd_tpu.engine import LLMEngine, SamplingParams
    from llmd_tpu.models.registry import get_model_config
    from llmd_tpu.serve.api import build_app
    from llmd_tpu.serve.async_engine import AsyncEngine
    from llmd_tpu.serve.tokenizer import ByteTokenizer
    from llmd_tpu.sidecar.proxy import SidecarConfig, build_sidecar_app

    ISL, N = 512, 12
    model = get_model_config("llama-3.2-3b", num_layers=12, max_model_len=1024)

    def make_engine(role):
        return LLMEngine(EngineConfig(
            model=model,
            cache=CacheConfig(page_size=16, num_blocks=512, dtype="bfloat16"),
            scheduler=SchedulerConfig(
                max_num_seqs=8, max_num_batched_tokens=1024, decode_window=1
            ),
            parallel=ParallelConfig(tensor_parallel_size=1),
            kv_role=role,
            kv_transfer_port=0,
        ))

    prefill = make_engine("kv_producer")
    decode = make_engine("kv_consumer")
    rng = np.random.default_rng(2)
    # Warm every program shape each side needs (prefill bucket + 1-token
    # decode + the P side's 1-token generation) so TTFT measures serving,
    # not compilation.
    warm_sp = SamplingParams(temperature=0.0, max_tokens=2, ignore_eos=True)
    for eng in (prefill, decode):
        eng.generate(
            [list(rng.integers(1, 255, size=ISL)) for _ in range(2)], warm_sp
        )

    prefill_srv = TestServer(
        build_app(AsyncEngine(prefill), ByteTokenizer(), "bench", 1024)
    )
    decode_srv = TestServer(
        build_app(AsyncEngine(decode), ByteTokenizer(), "bench", 1024)
    )
    await prefill_srv.start_server()
    await decode_srv.start_server()
    sidecar_srv = TestServer(
        build_sidecar_app(SidecarConfig(vllm_port=decode_srv.port), rank=0)
    )
    await sidecar_srv.start_server()

    ttfts = []
    try:
        async with ClientSession() as session:
            for i in range(N + 2):  # first two are HTTP/connection warmup
                prompt = "".join(
                    chr(c) for c in rng.integers(97, 122, size=ISL)
                )
                t0 = time.monotonic()
                async with session.post(
                    f"http://{sidecar_srv.host}:{sidecar_srv.port}/v1/completions",
                    json={
                        "prompt": prompt, "max_tokens": 4,
                        "temperature": 0.0, "stream": True,
                    },
                    headers={
                        "x-prefiller-host-port":
                            f"{prefill_srv.host}:{prefill_srv.port}"
                    },
                ) as resp:
                    assert resp.status == 200, await resp.text()
                    async for line in resp.content:
                        if line.startswith(b"data:") and b"[DONE]" not in line:
                            if i >= 2:
                                ttfts.append(time.monotonic() - t0)
                            break
                    async for _ in resp.content:
                        pass
    finally:
        for srv in (sidecar_srv, decode_srv, prefill_srv):
            await srv.close()
        for eng in (prefill, decode):
            if eng.kv_connector:
                eng.kv_connector.close()
    assert prefill.kv_connector.exported_requests >= N
    ttfts.sort()
    return ttfts[len(ttfts) // 2] * 1e3


def measure_dispatch_rtt_ms() -> float:
    """Median round-trip of a trivial compiled dispatch + device_get."""
    import jax
    import jax.numpy as jnp

    f = jax.jit(lambda x: x + 1)
    x = jnp.zeros((8,), jnp.float32)
    f(x).block_until_ready()
    samples = []
    for _ in range(5):
        t0 = time.monotonic()
        f(x).block_until_ready()
        samples.append(time.monotonic() - t0)
    samples.sort()
    return samples[len(samples) // 2] * 1e3


def main() -> None:
    toks_per_s = bench_dense("int8")
    extras = {"dispatch_rtt_ms": round(measure_dispatch_rtt_ms(), 1)}
    try:
        extras["dense_bf16_tok_s"] = round(bench_dense(None), 1)
    except Exception as e:  # pragma: no cover - keep the headline alive
        extras["dense_bf16_error"] = f"{type(e).__name__}: {e}"[:200]
    try:
        extras["mla_moe_tok_s"] = round(bench_mla_moe(), 1)
    except Exception as e:  # pragma: no cover
        extras["mla_moe_error"] = f"{type(e).__name__}: {e}"[:200]
    try:
        extras["pd_ttft_p50_ms"] = round(asyncio.run(_bench_pd_ttft()), 1)
    except Exception as e:  # pragma: no cover
        extras["pd_ttft_error"] = f"{type(e).__name__}: {e}"[:200]

    print(
        json.dumps(
            {
                "metric": "output tokens/s/chip (llama-3.2-3b-class int8 "
                "W8A8, B=128 128in/64out, single chip, e2e engine)",
                "value": round(toks_per_s, 1),
                "unit": "tok/s/chip",
                "vs_baseline": round(toks_per_s / REFERENCE_PER_CHIP_TOKS, 3),
                "extras": extras,
            }
        )
    )


if __name__ == "__main__":
    main()
