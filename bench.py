"""Single-chip serving throughput benchmark (driver contract).

Prints ONE JSON line: {"metric", "value", "unit", "vs_baseline"}.

Workload: offline continuous-batching decode of a Llama-3.2-3B-class model
(bf16, random weights) on the available TPU chip -- batch 32, 128-token
prompts, 64 output tokens each, greedy. End-to-end through LLMEngine
(scheduler + paged KV + sampling included), so host overhead counts.

vs_baseline: ratio against the reference's closest per-chip decode figure,
~1,600 output tok/s per decode GPU (DeepSeek-R1 wide-EP on 32xH200,
reference guides/wide-ep-lws/README.md:271; see BASELINE.md). Different
model/chip class, so this is a tracking ratio, not a like-for-like claim.
"""

from __future__ import annotations

import json
import time

REFERENCE_PER_CHIP_TOKS = 1600.0  # wide-ep-lws/README.md:271


def main() -> None:
    import numpy as np

    from llmd_tpu.config import CacheConfig, EngineConfig, ParallelConfig, SchedulerConfig
    from llmd_tpu.engine import LLMEngine, SamplingParams
    from llmd_tpu.models.registry import get_model_config

    B, ISL, OSL = 128, 128, 64
    model = get_model_config("llama-3.2-3b", max_model_len=512)
    # Tuned for the tunnel-attached single chip: the ~100ms host-dispatch
    # RTT dominates small steps, so the whole prefill rides ONE batched
    # dispatch (B*ISL=16384 tokens) and the whole decode ONE fused
    # 64-step window. Measured ladder (same workload): dw=16/mbt=2048
    # 997 tok/s -> dw=32/4096 1209 -> dw=64/8192 1468 -> dw=64/16384 1777.
    cfg = EngineConfig(
        model=model,
        cache=CacheConfig(page_size=16, num_blocks=2048, dtype="bfloat16"),
        scheduler=SchedulerConfig(
            max_num_seqs=B, max_num_batched_tokens=16384, decode_window=64
        ),
        parallel=ParallelConfig(tensor_parallel_size=1),
        seed=0,
    )
    engine = LLMEngine(cfg)
    rng = np.random.default_rng(0)
    sampling = SamplingParams(temperature=0.0, max_tokens=OSL, ignore_eos=True)

    # Warmup run on throwaway prompts: triggers every compile the workload
    # shape needs (batched prefill + fused decode windows).
    warm = [list(rng.integers(1, model.vocab_size, size=ISL)) for _ in range(B)]
    engine.generate(warm, sampling)

    prompts = [list(rng.integers(1, model.vocab_size, size=ISL)) for _ in range(B)]
    t0 = time.monotonic()
    out = engine.generate(prompts, sampling)
    dt = time.monotonic() - t0
    total_out = sum(len(v) for v in out.values())
    assert total_out == B * OSL, (total_out, B * OSL)
    toks_per_s = total_out / dt

    print(
        json.dumps(
            {
                "metric": "output tokens/s/chip (llama-3.2-3b-class bf16, "
                "B=128 128in/64out, single chip, e2e engine)",
                "value": round(toks_per_s, 1),
                "unit": "tok/s/chip",
                "vs_baseline": round(toks_per_s / REFERENCE_PER_CHIP_TOKS, 3),
            }
        )
    )


if __name__ == "__main__":
    main()
