"""Single-chip serving benchmark (driver contract).

Prints ONE JSON line: {"metric", "value", "unit", "vs_baseline", "extras"}.

Headline: offline continuous-batching decode of a Llama-3.2-3B-class model
(W8A8 INT8 weights — the TPU counterpart of the serving precision the
reference's headline path uses, FP8 DeepGEMM, docker/Dockerfile.cuda:69-70)
— batch 256, 128-token prompts, 64 output tokens, greedy, end-to-end
through LLMEngine (scheduler + paged KV + sampling), so host overhead
counts. (B rose 128 -> 256 in r4: int8's halved weight bytes leave
bandwidth headroom a larger batch converts to throughput; measured
ladder in bench_dense.) vs_baseline: ratio against the reference's
closest per-chip decode figure, ~1,600 output tok/s per decode GPU
(DeepSeek-R1 wide-EP on 32xH200, reference guides/wide-ep-lws/
README.md:271; see BASELINE.md). Different model/chip class — a
tracking ratio, not a like-for-like claim.

extras (north-star shapes, BASELINE.json):
  dense_bf16_tok_s — same workload, bf16 weights + bf16 KV (r01/r02
                    headline basis; keeps the precision trade visible).
  weight_stream_gbps — effective weight-stream bandwidth of the bf16 run
                    (iterations/s x weight bytes): the roofline context
                    for a flat bf16 number.
  kv_int8_tok_s_isl384_b128 / kv_bf16_tok_s_isl384_b96max — int8 KV
                    pool at long context: 2x pages per HBM byte serves
                    B=128 at ISL 384 where bf16 OOMs at compile; on this
                    KV-read-bound chip that capacity does NOT raise
                    tok/s (see bench_kv_int8_long_context for the
                    honest framing; the pool's throughput win is
                    pd_kvint8's wire TTFT).
  mla_moe_tok_s   — decode tok/s on a DeepSeek-V2-Lite-geometry MLA+MoE
                    model (depth cut to 8 to fit one chip's HBM), INT8
                    grouped-GEMM expert backend (the reference's FP8
                    DeepGEMM role). The architecture the 2.2k tok/s/chip
                    north star names.
  pd_ttft_p50_ms  — p50 time-to-first-token through the FULL P/D path
                    (client -> sidecar -> prefill engine -> kvship KV
                    transfer -> decode engine first token) on localhost,
                    against the < 200 ms north-star target.
  dispatch_rtt_ms — measured host->device dispatch round-trip. Under the
                    axon tunnel this is ~100 ms (vs sub-ms co-located),
                    and the P/D path pays several dispatches plus two
                    ~25 MB HBM<->host stagings, so pd_ttft_p50_ms has an
                    environment floor far above the target; read it
                    relative to this RTT.
  roofline_int8 / roofline_bf16 — MFU and HBM-BW utilization context for
                    the raw tok/s headlines: config-derived FLOPs/token
                    and bytes/token against the chip's peak specs
                    (_roofline_extras; estimates, labeled as such).
  ragged_step     — flattened-token step (--ragged-qlens) CPU-sim part:
                    mixed-batch padded/live token ratio ragged vs
                    bucketed (target <= 0.15 vs multiples of it), with
                    byte-identical greedy AND seeded streams and the
                    window=1 shape-family counts.
  fault_degrade   — graceful-degradation CPU-sim part (fault-
                    tolerance.md): P/D throughput under a seeded 1%
                    kv.pull.drop FaultPlan vs the clean run (target
                    ratio >= 0.9, recorded), with the recompute
                    fallback proven engaged and streams byte-identical.
  fleet_soak      — fleet-scale chaos-soak CPU-sim part (fleet-soak.md):
                    the replica-kill + steady scenarios over the REAL
                    EPP/flow-control/breaker/autoscale stack on a
                    virtual-time loop at reduced scale — zero requests
                    lost to mid-stream crashes, bounded time-to-reroute,
                    breaker-open visible, byte-identical scoreboards
                    across two runs (the full >=10^4-QPS matrix runs in
                    the CI `soak` job).
  kv_federation   — cross-replica KV-federation CPU-sim part
                    (kv-federation.md): the kv_federation fleetsim
                    scenario federated vs cold (store tier disabled) on
                    the same trace — recompute_avoided_ratio (> 0, the
                    fleet-wide reuse headline), exact virtual-time
                    federated-vs-cold p50 TTFT ratio, byte-identical
                    scoreboards across two federated runs.
  stream_resume   — mid-stream failover CPU-sim part (fault-
                    tolerance.md stream continuation contract): the
                    replica_kill fleetsim scenario (store tier armed)
                    — kill-at-p50 resume TTFT vs the deterministic
                    cold-recompute cost, zero client-visible stream
                    failures, stitched streams byte-identical, plus
                    the router_soak leg driving the REAL aiohttp
                    router's resume path over loopback sockets.
  batch_backfill  — batch serving tier CPU-sim part
                    (batch-processing.md): the batch_backfill fleetsim
                    scenario batch-on vs no-batch on the same diurnal
                    interactive trace — batch tok/s harvested from
                    trough capacity, trough-utilization lift, backlog
                    drained, and the interactive p99 TTFT on/off ratio
                    (the zero-regression headline), byte-identical
                    scoreboards across two batch-on runs.
  lora_pool       — multi-tenant LoRA CPU-sim part
                    (multi-tenant-lora.md): a real-engine 2-slot paged
                    adapter pool under mixed-tenant churn vs a
                    single-adapter baseline (cold-load TTFT ratio,
                    eviction counts, resident-vs-cold byte parity
                    greedy+seeded), plus the lora_tenant fleetsim
                    scenario affinity-routed vs adapter-blind — the
                    exact virtual-time resident-hit-ratio lift.
  moe_ep          — wide-EP dispatch-path CPU-sim part (wide-ep.md):
                    the real moe_block_ep census on the 8-device
                    virtual mesh — hot-expert required capacity and
                    drops before vs after the real EPLB placement,
                    AdaptiveCapacity converging below static 2.0 at
                    zero drops (fewer padded slots, smaller a2a
                    payload), and the expert_skew fleetsim scenario's
                    EPLB-on-vs-identity comparison at reduced scale.
  moe_overlap     — microbatched overlapped expert dispatch on/off
                    step time on the virtual CPU mesh; byte-identity
                    gated in tests, flag default off, graduates on a
                    real-slice win (same contract as dbo).
  pd_stream       — layer-streamed disaggregated TTFT CPU-sim part
                    (kv-cache.md "layer-streamed import"): the full
                    sidecar two-phase P->D stack at a CPU-compilable
                    size — streamed local/cached p50 TTFT vs the
                    < 200 ms acceptance target, the v3 group-framed
                    wire's fetch->CRC->scatter pipeline with the
                    first-group admission seam (overlap ratio), a
                    monolithic (v2) wire comparison, and a per-stage
                    waterfall that provably sums to the measured TTFT.
"""

from __future__ import annotations

import asyncio
import json
import time

REFERENCE_PER_CHIP_TOKS = 1600.0  # wide-ep-lws/README.md:271


# Peak per-chip specs for the roofline context (dense matmul peak at
# the compute dtype, HBM bandwidth), keyed by a device_kind substring.
# Sources: public TPU spec sheets; the bench only needs the right order
# of magnitude to turn raw tok/s into MFU / BW-utilization context.
_CHIP_PEAKS = {
    # kind-substring: (bf16 FLOP/s, int8 OP/s, HBM bytes/s)
    "v5 lite": (197e12, 394e12, 819e9),
    "v5e": (197e12, 394e12, 819e9),
    "v5p": (459e12, 918e12, 2765e9),
    "v4": (275e12, 275e12, 1228e9),
    "v6e": (918e12, 1836e12, 1640e9),
    "v6 lite": (918e12, 1836e12, 1640e9),
}


def _roofline_extras(model, engine, tok_s, B, ISL, OSL, quantization):
    """MFU / HBM-BW context next to the raw tok/s headline (ROADMAP
    "Recent" debt): model FLOPs/token and bytes/token DERIVED FROM
    CONFIG — 2 x matmul params per token plus the attention score/value
    matmuls at the workload's mean context — against the chip's peak
    specs. Estimates, labeled as such: the point is knowing whether a
    headline sits at 2% or 40% of the chip, not a third decimal."""
    import jax

    matmul_params = 0
    for path, leaf in jax.tree_util.tree_flatten_with_path(
        engine.runner.params
    )[0]:
        name = str(path[-1])
        if "embed" in name or "_scale" in name or "norm" in name:
            continue
        matmul_params += leaf.size
    mean_ctx = ISL + OSL / 2
    cfg = model
    attn_flops = 4.0 * cfg.num_layers * cfg.num_heads * cfg.head_dim * mean_ctx
    flops_per_token = 2.0 * matmul_params + attn_flops
    wbytes = sum(
        leaf.size * leaf.dtype.itemsize
        for leaf in jax.tree.leaves(engine.runner.params)
    )
    kv_elt = 1 if engine.runner.kv_quantized else jax.numpy.dtype(
        engine.config.cache.dtype
    ).itemsize
    kv_read = (
        2 * cfg.num_layers * cfg.num_kv_heads * cfg.head_dim
        * mean_ctx * kv_elt
    )
    # Decode streams the full weight set once per ITERATION (whole
    # batch), so per token it is wbytes / B; each token also reads its
    # own KV context.
    bytes_per_token = wbytes / B + kv_read
    kind = jax.devices()[0].device_kind.lower()
    peak = next(
        (v for sub, v in _CHIP_PEAKS.items() if sub in kind), None
    )
    out = {
        "flops_per_token": round(flops_per_token),
        "bytes_per_token": round(bytes_per_token),
        "device_kind": jax.devices()[0].device_kind,
        "note": (
            "config-derived estimates (2 x matmul params + attention at "
            "mean context); mfu against the dense matmul peak at the "
            "compute dtype, hbm_bw_util against the HBM spec ceiling"
        ),
    }
    if peak is not None:
        bf16_peak, int8_peak, hbm = peak
        compute_peak = int8_peak if quantization == "int8" else bf16_peak
        out["mfu"] = round(tok_s * flops_per_token / compute_peak, 4)
        out["hbm_bw_util"] = round(tok_s * bytes_per_token / hbm, 4)
    else:
        out["mfu"] = out["hbm_bw_util"] = None
    return out


def bench_dense(quantization: str | None = "int8", kv_dtype: str = "bfloat16"):
    import numpy as np

    import jax

    from llmd_tpu.config import (
        CacheConfig, EngineConfig, ParallelConfig, SchedulerConfig,
    )
    from llmd_tpu.engine import LLMEngine, SamplingParams
    from llmd_tpu.models.registry import get_model_config

    # INT8 runs at B=256: halved weight bytes leave bandwidth headroom
    # that a LARGER batch converts to throughput (measured ladder r4,
    # same workload/chip: B=128 4,224 -> 192 4,626 -> 256 4,680-4,830
    # across runs -> 320 OOM). bf16 keeps the r1-r3 shape (B=128; its
    # weight stream already saturates, and B=256 bf16 KV+weights exceed
    # HBM).
    B = 256 if quantization == "int8" else 128
    ISL, OSL = 128, 64
    model = get_model_config(
        "llama-3.2-3b", max_model_len=512, quantization=quantization
    )
    # Tuned for the tunnel-attached single chip: the ~100ms host-dispatch
    # RTT dominates small steps, so the whole prefill rides ONE batched
    # dispatch (B*ISL tokens) and the whole decode ONE fused 64-step
    # window. Earlier ladder (B=128): dw=16/mbt=2048 997 tok/s ->
    # dw=32/4096 1209 -> dw=64/8192 1468 -> dw=64/16384 1777; page=32
    # measured worse (3,244) than page=16.
    # kv_dtype="int8": same HBM budget holds 2x the pages.
    cfg = EngineConfig(
        model=model,
        cache=CacheConfig(
            page_size=16,
            num_blocks=4096 if (kv_dtype == "int8" or B > 128) else 2048,
            dtype=kv_dtype,
        ),
        scheduler=SchedulerConfig(
            max_num_seqs=B, max_num_batched_tokens=B * ISL, decode_window=64
        ),
        parallel=ParallelConfig(tensor_parallel_size=1),
        seed=0,
    )
    engine = LLMEngine(cfg)
    rng = np.random.default_rng(0)
    sampling = SamplingParams(temperature=0.0, max_tokens=OSL, ignore_eos=True)
    warm = [list(rng.integers(1, model.vocab_size, size=ISL)) for _ in range(B)]
    engine.generate(warm, sampling)

    prompts = [list(rng.integers(1, model.vocab_size, size=ISL)) for _ in range(B)]
    t0 = time.monotonic()
    out = engine.generate(prompts, sampling)
    dt = time.monotonic() - t0
    total_out = sum(len(v) for v in out.values())
    assert total_out == B * OSL, (total_out, B * OSL)
    # Roofline note: each decode iteration streams the full weight set
    # once for the whole batch, so effective weight-stream bandwidth
    # = iterations/s x weight bytes = (tok_s / B) x sum(param bytes).
    # Compare against the chip's effective HBM ceiling to see whether
    # the dense number is bandwidth-bound (axon v5e measures ~150GB/s
    # effective through this path).
    wbytes = sum(
        leaf.size * leaf.dtype.itemsize
        for leaf in jax.tree.leaves(engine.runner.params)
    )
    tok_s = total_out / dt
    stream_gbps = tok_s / B * wbytes / 1e9
    roofline = _roofline_extras(model, engine, tok_s, B, ISL, OSL, quantization)
    del engine
    return tok_s, stream_gbps, roofline


def bench_mla_moe():
    """DeepSeek-family decode: MLA latent KV + grouped-GEMM MoE experts."""
    import numpy as np

    from llmd_tpu.config import (
        CacheConfig, EngineConfig, ParallelConfig, SchedulerConfig,
    )
    from llmd_tpu.engine import LLMEngine, SamplingParams
    from llmd_tpu.models.registry import get_model_config

    B, ISL, OSL = 128, 128, 64
    # V2-Lite geometry (MLA rank 512+64, 64 experts top-6, shared expert,
    # dense first layer) at depth 8: ~4B params fit one chip. INT8 experts
    # stream half the bytes through the grouped GEMM — the quantized-
    # serving shape the reference runs this architecture in (FP8 DeepGEMM).
    model = get_model_config(
        "deepseek-v2-lite", num_layers=8, max_model_len=512,
        quantization="int8",
    )
    cfg = EngineConfig(
        model=model,
        cache=CacheConfig(page_size=16, num_blocks=2048, dtype="bfloat16"),
        scheduler=SchedulerConfig(
            max_num_seqs=B, max_num_batched_tokens=16384, decode_window=64
        ),
        parallel=ParallelConfig(tensor_parallel_size=1, moe_backend="grouped"),
        seed=0,
    )
    engine = LLMEngine(cfg)
    rng = np.random.default_rng(1)
    sampling = SamplingParams(temperature=0.0, max_tokens=OSL, ignore_eos=True)
    warm = [list(rng.integers(1, model.vocab_size, size=ISL)) for _ in range(B)]
    engine.generate(warm, sampling)

    prompts = [list(rng.integers(1, model.vocab_size, size=ISL)) for _ in range(B)]
    t0 = time.monotonic()
    out = engine.generate(prompts, sampling)
    dt = time.monotonic() - t0
    total_out = sum(len(v) for v in out.values())
    assert total_out == B * OSL, (total_out, B * OSL)
    del engine
    return total_out / dt


def bench_kv_int8_long_context():
    """The int8 KV pool at long context (ISL 384 of a 512 window),
    honestly framed. CAPACITY: B=128 needs 3,584 pages — the bf16 pool
    cannot fit that next to the weights on this chip (compile-time OOM);
    the int8 pool serves it. THROUGHPUT (r5 rework, measured stage by
    stage): the r4 deficit was the SCALE WRITE path, not the kernel or
    the scale gather — the per-(token,head) scale scatter enumerated
    T*K eight-byte updates (scatter cost is per-update, and a
    const-scales probe showed kernel + gather are within noise of the
    bf16 path). Prefill now scatters [K,2] windows per token and decode
    rewrites whole [K,page,2] slabs; with that, decode at capacity
    B=128 runs 0.192 ms/seq/tok vs bf16's 0.196 at its feasible B=96.
    Residual at EQUAL B=96: ~10% — the quantize/dequant work an int8
    pool inherently pays, which short-ISL prefill can't amortize. The
    pool's wins: capacity (B=128 serves at all), long-OSL decode, and
    the wire (pd_kvint8 ships pool bytes directly — half bytes, zero
    quantize work). Reference precedent: FP8 KV on the flagship path
    (Dockerfile.cuda:69-70)."""
    return {
        "kv_int8_tok_s_isl384_b128": _bench_long_ctx("int8", 128, 4096),
        # xfail-style regression note (r6 hunt over the captured r04
        # deficit, 1,518 vs bf16's 1,845 on its home turf): the r5 scale-
        # WRITE fix above addressed the largest stage, but the captured
        # record predates it (BENCH_r05 died rc=124) so the deficit
        # stands un-requalified. Remaining ranked suspects, from reading
        # the decode attention's int8-only work: (1) the per-layer scale
        # GATHER+RELAYOUT plane ([B, K, 2, max_pages*page]) scales with
        # the TABLE width, not the live context — r6 halves it by
        # shipping f16 scales (lossless: pool scales live on the f16
        # grid; ragged_paged_attention.py) — and (2) the inherent
        # per-block dequant multiplies on the [K, G, S] score plane,
        # which equal-B parity (~10%) already prices. Requalify on the
        # next captured chip run; if the f16-plane halving doesn't close
        # it, the residual is (2) and the pool's honest wins stay
        # capacity + wire bytes, not same-B throughput.
        "kv_int8_note": (
            "captured 0.82x vs bf16 predates the r5 scale-write fix and "
            "the r6 f16 scale-plane halving; expected to close or "
            "attribute to inherent dequant cost on requalification"
        ),
    }


def bench_kv_bf16_long_context():
    return {
        "kv_bf16_tok_s_isl384_b96max": _bench_long_ctx("bfloat16", 96, 2816)
    }


def _bench_long_ctx(kv_dtype: str, B: int, blocks: int) -> float:
    import numpy as np

    from llmd_tpu.config import (
        CacheConfig, EngineConfig, ParallelConfig, SchedulerConfig,
    )
    from llmd_tpu.engine import LLMEngine, SamplingParams
    from llmd_tpu.models.registry import get_model_config

    ISL, OSL = 384, 64
    model = get_model_config(
        "llama-3.2-3b", max_model_len=512, quantization="int8"
    )
    cfg = EngineConfig(
        model=model,
        cache=CacheConfig(page_size=16, num_blocks=blocks, dtype=kv_dtype),
        scheduler=SchedulerConfig(
            # One-shot prefill (B x ISL in a single dispatch) — the same
            # tunnel-RTT-amortizing philosophy as the headline config.
            max_num_seqs=B, max_num_batched_tokens=B * ISL, decode_window=64
        ),
        parallel=ParallelConfig(tensor_parallel_size=1),
        seed=0,
    )
    engine = LLMEngine(cfg)
    rng = np.random.default_rng(0)
    sp = SamplingParams(temperature=0.0, max_tokens=OSL, ignore_eos=True)
    engine.generate(
        [list(rng.integers(1, model.vocab_size, size=ISL)) for _ in range(B)],
        sp,
    )
    prompts = [
        list(rng.integers(1, model.vocab_size, size=ISL)) for _ in range(B)
    ]
    t0 = time.monotonic()
    res = engine.generate(prompts, sp)
    dt = time.monotonic() - t0
    assert sum(len(v) for v in res.values()) == B * OSL
    del engine
    return round(B * OSL / dt, 1)


def bench_swa_ring(ring: bool):
    """SWA ring pool (--kv-swa-ring; the reference's hybrid KV cache
    manager role, pd patch-decode.yaml:19) on a gpt-oss-geometry proxy.

    Two claims, measured separately because they have different honest
    substrates: (1) tok/s ring-on vs ring-off on the SAME e2e workload —
    the ring changes memory layout, not attention work (the window-skip
    already avoids out-of-window reads either way). Measured on this
    proxy: ~203-207 off vs ~185 on (reproducible ~10% overhead: two-pool
    scan carries + the per-dispatch ring-view table). (2) per-sequence
    KV bytes AT max_model_len — exact geometry math, where the ring's
    win lives (sliding layers hold R pages instead of ctx/page): at the
    real gpt-oss-20b shape (24 layers alternating at window 128, ctx
    131072) the ratio is 0.508 — 6.0 -> 3.05 GB/seq. Like the int8
    pool, the flag buys CAPACITY (2x the concurrent long sequences per
    HBM byte), not single-batch speed.

    The on/off runs live in SEPARATE bench parts (subprocesses): two
    engines in one process RESOURCE_EXHAUST the tunnel chip (lagging
    arena reclaim between engine lifetimes — same reason main() runs
    every part in a subprocess)."""
    import numpy as np

    from llmd_tpu.config import (
        CacheConfig, EngineConfig, ParallelConfig, SchedulerConfig,
        swa_ring_spec,
    )
    from llmd_tpu.engine import LLMEngine, SamplingParams
    from llmd_tpu.models.registry import get_model_config

    B, ISL, OSL = 32, 1024, 64
    # Depth 4 + vocab 32768 + 8-row prefill dispatches: the 32-expert
    # layers cost ~0.8G/layer int8 and the MoE prefill temps ~0.25M/token,
    # so deeper/wider proxies RESOURCE_EXHAUST this 16G chip.
    proxy = get_model_config(
        "gpt-oss-20b", num_layers=4,
        layer_types=tuple(
            "sliding_attention" if i % 2 == 0 else "full_attention"
            for i in range(4)
        ),
        max_model_len=8192, quantization="int8", vocab_size=32768,
    )

    def run(ring: bool):
        cfg = EngineConfig(
            model=proxy,
            cache=CacheConfig(
                page_size=16, num_blocks=2304, dtype="bfloat16",
                swa_ring=ring,
                # Ring-on force-disables prefix caching; the off run must
                # match or its per-page hashing slows it and the A/B
                # conflates two effects.
                enable_prefix_caching=False,
            ),
            scheduler=SchedulerConfig(
                max_num_seqs=B, max_num_batched_tokens=8 * ISL,
                decode_window=64,
            ),
            parallel=ParallelConfig(tensor_parallel_size=1),
            seed=0,
        )
        engine = LLMEngine(cfg)
        rng = np.random.default_rng(2)
        sp = SamplingParams(temperature=0.0, max_tokens=OSL, ignore_eos=True)
        mk = lambda: [  # noqa: E731
            list(rng.integers(1, proxy.vocab_size, size=ISL)) for _ in range(B)
        ]
        engine.generate(mk(), sp)
        t0 = time.monotonic()
        out = engine.generate(mk(), sp)
        dt = time.monotonic() - t0
        assert sum(len(v) for v in out.values()) == B * OSL
        del engine
        return round(B * OSL / dt, 1)

    if not ring:
        return {"swa_off_tok_s": run(False)}

    # Exact per-seq KV bytes at max context, real gpt-oss-20b geometry.
    model = get_model_config("gpt-oss-20b")
    cache = CacheConfig(page_size=16, swa_ring=True)
    sched = SchedulerConfig(max_num_seqs=1, max_num_batched_tokens=2048)
    spec = swa_ring_spec(model, cache, sched)
    page_bytes = (
        model.kv_cache_heads * cache.page_size * model.kv_cache_entry_dim * 2
    )
    pages_full_len = model.max_model_len // cache.page_size
    per_seq_off = pages_full_len * model.num_layers * page_bytes
    per_seq_on = (
        pages_full_len * len(spec.full_layers)
        + spec.ring_pages * len(spec.swa_layers)
    ) * page_bytes
    return {
        "swa_on_tok_s": run(True),
        "gpt_oss_20b_kv_per_seq_at_131k_gb": round(per_seq_off / 2**30, 2),
        "gpt_oss_20b_kv_per_seq_ring_gb": round(per_seq_on / 2**30, 2),
        "kv_per_seq_ratio": round(per_seq_on / per_seq_off, 3),
    }


async def _bench_pd_ttft(
    transfer_dtype: str = "auto",
    kv_dtype: str = "bfloat16",
    local_fastpath: bool = False,
    cached_repeat: bool = False,
    stream_groups: int | None = None,
    model_cfg=None,
    isl: int = 512,
    n_requests: int = 12,
    page_size: int = 16,
    num_blocks: int = 512,
):
    """p50 TTFT through sidecar two-phase P->D with a real KV transfer.

    transfer_dtype="int8" measures the opt-in quantized transfer encoding
    (half the staging bytes — the dominant cost on this tunnel).
    kv_dtype="int8" runs int8 POOLS on both sides: the q8 wire form ships
    the pool bytes directly (half bytes AND no quantize work).
    local_fastpath=False keeps the WIRE path honest even though both
    bench engines share this process (the default-on fast path would
    claim device snapshots directly); the pd_local part measures it on.
    cached_repeat=True measures the byte-diet warm case: every request
    repeats ONE prompt, so from request 2 on the decode cache holds the
    full prefix and the probe makes the producer stage nothing.
    stream_groups pins the v3 layer-group stream width (None = engine
    default, 1 = the monolithic v2 wire — the streamed-vs-monolithic
    comparison leg); model_cfg/isl/... let the CPU-sim pd_stream part
    reuse this harness at a CPU-compilable size.

    Returns (p50_ms, stages) where ``stages`` includes the per-stage
    WATERFALL of the last measured request: consecutive monotonic
    milestone differences (request start -> fetch start -> first group
    -> fetch done -> apply done -> first token) that telescope, so they
    provably sum to that request's measured TTFT within clock epsilon.
    """
    import numpy as np
    from aiohttp import ClientSession
    from aiohttp.test_utils import TestServer

    from llmd_tpu.config import (
        CacheConfig, EngineConfig, ParallelConfig, SchedulerConfig,
    )
    from llmd_tpu.engine import LLMEngine, SamplingParams
    from llmd_tpu.models.registry import get_model_config
    from llmd_tpu.serve.api import build_app
    from llmd_tpu.serve.async_engine import AsyncEngine
    from llmd_tpu.serve.tokenizer import ByteTokenizer
    from llmd_tpu.sidecar.proxy import SidecarConfig, build_sidecar_app

    ISL, N = isl, n_requests
    model = model_cfg or get_model_config(
        "llama-3.2-3b", num_layers=12, max_model_len=1024
    )

    def make_engine(role):
        return LLMEngine(EngineConfig(
            model=model,
            cache=CacheConfig(
                page_size=page_size, num_blocks=num_blocks, dtype=kv_dtype
            ),
            scheduler=SchedulerConfig(
                max_num_seqs=8, max_num_batched_tokens=1024, decode_window=1
            ),
            parallel=ParallelConfig(tensor_parallel_size=1),
            kv_role=role,
            kv_transfer_port=0,
            kv_transfer_dtype=transfer_dtype,
            kv_local_fastpath=local_fastpath,
            **(
                {} if stream_groups is None
                else {"kv_stream_groups": stream_groups}
            ),
        ))

    prefill = make_engine("kv_producer")
    decode = make_engine("kv_consumer")
    rng = np.random.default_rng(2)
    # Warm every program shape each side needs (prefill bucket + 1-token
    # decode + the P side's 1-token generation) so TTFT measures serving,
    # not compilation.
    warm_sp = SamplingParams(temperature=0.0, max_tokens=2, ignore_eos=True)
    for eng in (prefill, decode):
        eng.generate(
            [list(rng.integers(1, 255, size=ISL)) for _ in range(2)], warm_sp
        )

    prefill_srv = TestServer(
        build_app(AsyncEngine(prefill), ByteTokenizer(), "bench", 1024)
    )
    decode_srv = TestServer(
        build_app(AsyncEngine(decode), ByteTokenizer(), "bench", 1024)
    )
    await prefill_srv.start_server()
    await decode_srv.start_server()
    sidecar_srv = TestServer(
        build_sidecar_app(SidecarConfig(vllm_port=decode_srv.port), rank=0)
    )
    await sidecar_srv.start_server()

    ttfts = []
    last_t0 = last_first = None
    try:
        async with ClientSession() as session:
            fixed = "".join(chr(c) for c in rng.integers(97, 122, size=ISL))
            for i in range(N + 2):  # first two are HTTP/connection warmup
                prompt = fixed if cached_repeat else "".join(
                    chr(c) for c in rng.integers(97, 122, size=ISL)
                )
                t0 = time.monotonic()
                async with session.post(
                    f"http://{sidecar_srv.host}:{sidecar_srv.port}/v1/completions",
                    json={
                        "prompt": prompt, "max_tokens": 4,
                        "temperature": 0.0, "stream": True,
                    },
                    headers={
                        "x-prefiller-host-port":
                            f"{prefill_srv.host}:{prefill_srv.port}"
                    },
                ) as resp:
                    assert resp.status == 200, await resp.text()
                    async for line in resp.content:
                        if line.startswith(b"data:") and b"[DONE]" not in line:
                            if i >= 2:
                                ttfts.append(time.monotonic() - t0)
                                last_t0, last_first = (
                                    t0, time.monotonic()
                                )
                            break
                    async for _ in resp.content:
                        pass
    finally:
        for srv in (sidecar_srv, decode_srv, prefill_srv):
            await srv.close()
        for eng in (prefill, decode):
            if eng.kv_connector:
                eng.kv_connector.close()
    assert prefill.kv_connector.exported_requests >= N
    ttfts.sort()
    p_stats = prefill.kv_connector.stats()
    d_stats = decode.kv_connector.stats()
    if transfer_dtype == "adaptive":
        # The decision inputs + outcome: measured staging throughput per
        # ORIGINAL byte for each encoding on THIS link, and which one
        # the producer converged to.
        stages = {
            "enc_rate_exact_mbps": p_stats["enc_rate_exact_mbps"],
            "enc_rate_q8_mbps": p_stats["enc_rate_q8_mbps"],
            "picked": (
                "q8"
                if p_stats["enc_rate_q8_mbps"] > p_stats["enc_rate_exact_mbps"]
                else "exact"
            ),
        }
        return ttfts[len(ttfts) // 2] * 1e3, stages
    # Per-stage budget of the last transfer (the pipelined path: the
    # producer responds after prefill compute; its HBM->host staging
    # overlaps the consumer's pull-wait + device uploads, so fetch_ms
    # ~= the one staging leg that remains on the critical path).
    stages = {
        "producer_stage_ms": p_stats["last_stage_ms"],
        "consumer_fetch_ms": d_stats["last_fetch_ms"],
        "consumer_apply_ms": d_stats["last_apply_ms"],
        # Layer-streamed import: how long the decode side waited before
        # becoming schedulable (group 0 resident) on each side's clock.
        "producer_first_group_ms": p_stats["last_first_group_ms"],
        "consumer_first_group_ms": d_stats["last_first_group_ms"],
        "stream_groups_cells": d_stats["stream_groups_total"],
    }
    # The WATERFALL of the last measured request: consecutive segments
    # of one monotonic timeline (request start -> fetch start -> first
    # group -> fetch done -> apply done -> first token). Telescoping
    # differences, so sum(waterfall) == measured TTFT up to the two
    # clock reads bracketing the HTTP write (epsilon, asserted by the
    # CI summary check on the CPU-sim part).
    tl = dict(decode.kv_connector.last_timeline)
    if last_t0 is not None and tl.get("fetch_start"):
        fs = tl["fetch_start"]
        fg = tl.get("first_group", tl.get("fetch_done", fs))
        fd = tl.get("fetch_done", fg)
        ad = tl.get("apply_done", fd)
        ttft_ms = (last_first - last_t0) * 1e3
        waterfall = {
            # sidecar probe + phase-1 prefill + HTTP until the consumer
            # fetch starts
            "phase1_ms": round((fs - last_t0) * 1e3, 3),
            # admission gate: wire/claim until group 0 resident
            "first_group_ms": round((fg - fs) * 1e3, 3),
            # remaining groups streaming while the request is parked/
            # scheduled — the OVERLAPPED leg
            "stream_rest_ms": round((fd - fg) * 1e3, 3),
            # stream resolution -> hash-chain commit at a step boundary
            "apply_ms": round((ad - fd) * 1e3, 3),
            # tail prefill + first decode token
            "decode_ms": round((last_first - ad) * 1e3, 3),
        }
        stages["waterfall"] = waterfall
        stages["waterfall_total_ms"] = round(
            sum(waterfall.values()), 3
        )
        stages["last_ttft_ms"] = round(ttft_ms, 3)
        span = fd - fs
        stages["overlap_ratio"] = round(
            (fd - fg) / span, 3
        ) if span > 0 else 0.0
    return ttfts[len(ttfts) // 2] * 1e3, stages


def bench_env_probes() -> dict:
    """Environment controls for the P/D wire numbers.

    The wire TTFT rides three links whose day-to-day variance (the tunnel)
    is otherwise indistinguishable from a code regression: raw TCP
    loopback (the shipper's socket path), device->host staging (the
    producer's download leg), and host->device staging (the consumer's
    upload leg). Recording all three lets round-over-round wire numbers
    be normalized against the substrate they ran on."""
    import socket
    import threading

    import numpy as np

    out = {}
    # --- raw TCP loopback ---
    total = 256 << 20
    srv = socket.create_server(("127.0.0.1", 0))
    got = threading.Event()

    def sink():
        conn, _ = srv.accept()
        n = 0
        while n < total:
            b = conn.recv(1 << 20)
            if not b:
                break
            n += len(b)
        conn.close()
        got.set()

    threading.Thread(target=sink, daemon=True).start()
    c = socket.create_connection(("127.0.0.1", srv.getsockname()[1]))
    buf = b"\0" * (8 << 20)
    t0 = time.monotonic()
    for _ in range(total // len(buf)):
        c.sendall(buf)
    if got.wait(timeout=60):
        out["loopback_gbps"] = round(
            total / (time.monotonic() - t0) / 2**30, 2
        )
    else:
        # A wedged sink must not record a plausible-but-wrong number —
        # the probe exists to DISAMBIGUATE environment vs regression.
        out["loopback_error"] = "sink did not drain within 60s"
    c.close()
    srv.close()

    # --- device<->host staging (the tunnel's data plane) ---
    import jax
    import jax.numpy as jnp

    x = np.zeros((16 << 20) // 4, np.float32)  # 16 MB
    # The download probe must fetch DEVICE-COMPUTED data: a device_put
    # array keeps a host mirror and device_get short-circuits to memcpy
    # speed, reporting fantasy bandwidth.
    make = jax.jit(lambda s: jnp.full(x.shape, 1.0, jnp.float32) * s)
    h2d, d2h = [], []
    for i in range(3):
        t0 = time.monotonic()
        jax.device_put(x).block_until_ready()
        h2d.append(time.monotonic() - t0)
        d = make(float(i))
        d.block_until_ready()
        t0 = time.monotonic()
        np.asarray(jax.device_get(d))
        d2h.append(time.monotonic() - t0)
    out["host_to_device_gbps"] = round(x.nbytes / sorted(h2d)[1] / 2**30, 3)
    out["device_to_host_gbps"] = round(x.nbytes / sorted(d2h)[1] / 2**30, 3)
    return out


def bench_predictor_real() -> dict:
    """Latency-predictor accuracy against MEASURED engine timings.

    The r4 number was circular: trained and evaluated on the synthetic
    generator whose functional form the features share (VERDICT r4 weak
    7). Here a real engine serves a mixed trace (bursty arrivals, varied
    ISL, some repeated prompts for prefix hits) on this chip; each
    request's submission-time stats snapshot is the feature vector and
    its measured first-token latency the label; evaluation is
    prequential (predict-then-observe). Reference bar: ~5% MAPE against
    real served traffic (latency-predictor.md:58); on THIS substrate the
    floor is far higher — first tokens land on ~100 ms tunnel-RTT step
    boundaries and a burst completes in one batched prefill, so
    feature-identical requests get different TTFTs (and vice versa).
    The mean is outlier-skewed; the median is the stabler read. The
    point of this part is that the number is no longer circular."""
    import numpy as np

    from llmd_tpu.config import (
        CacheConfig, EngineConfig, ParallelConfig, SchedulerConfig,
    )
    from llmd_tpu.engine import LLMEngine, SamplingParams
    from llmd_tpu.models.registry import get_model_config
    from llmd_tpu.predictor.model import LatencyPredictor, ttft_features

    model = get_model_config("llama-3.2-3b", num_layers=4, max_model_len=512)
    engine = LLMEngine(EngineConfig(
        model=model,
        cache=CacheConfig(page_size=16, num_blocks=1024, dtype="bfloat16"),
        scheduler=SchedulerConfig(
            max_num_seqs=16, max_num_batched_tokens=2048, decode_window=4
        ),
        parallel=ParallelConfig(tensor_parallel_size=1),
        seed=0,
    ))
    rng = np.random.default_rng(7)
    sp = SamplingParams(temperature=0.0, max_tokens=8, ignore_eos=True)
    # Warm the step shapes so compiles don't pollute the labels.
    engine.generate(
        [list(rng.integers(1, 255, size=s)) for s in (64, 384)], sp
    )

    N = 480
    repeat_pool = [
        list(rng.integers(1, 255, size=int(s)))
        for s in rng.integers(64, 384, size=8)
    ]
    submitted = 0
    inflight_tokens = 0
    pending: dict[str, tuple[float, list, int]] = {}
    samples: list[tuple[list, float]] = []
    while submitted < N or engine.has_work():
        # Bursty arrivals up to 1.5x the batch width: real queueing
        # delays (multiple scheduler rounds) so TTFT's dynamic range is
        # feature-driven, not dominated by one-step dispatch noise.
        if submitted < N and engine.scheduler.num_waiting < 8:
            for _ in range(int(rng.integers(1, 25))):
                if submitted >= N:
                    break
                if rng.random() < 0.25:
                    prompt = repeat_pool[int(rng.integers(len(repeat_pool)))]
                    prefix = 1.0
                else:
                    prompt = list(
                        rng.integers(1, 255, size=int(rng.integers(32, 500)))
                    )
                    prefix = 0.0
                # LIVE scheduler/allocator state, not engine.stats: the
                # stats gauges refresh at step end, so every request in
                # a burst would see identical stale queue features.
                feats = ttft_features(
                    engine.allocator.usage(),
                    engine.scheduler.num_waiting,
                    engine.scheduler.num_running,
                    len(prompt), prefix, inflight_tokens,
                )
                rid = engine.add_request(prompt, sp)
                pending[rid] = (time.monotonic(), feats, len(prompt) + 8)
                inflight_tokens += len(prompt) + 8
                submitted += 1
        for out in engine.step():
            entry = pending.get(out.request_id)
            if entry is None:
                continue
            t0, feats, toks = entry
            if feats is not None and out.new_token_ids:
                samples.append((feats, (time.monotonic() - t0) * 1e3))
                # Sampled, but the request stays pending until finished
                # so inflight_tokens bookkeeping balances.
                pending[out.request_id] = (t0, None, toks)
            if out.finished:
                del pending[out.request_id]
                inflight_tokens -= toks
    del engine
    # Prequential (predict-THEN-observe) evaluation after a warmup: the
    # honest analog of the reference's continuously retraining sidecar
    # (latency-predictor.md:20-41) — every prediction uses only the
    # past, and the trainer has seen recent traffic, exactly as in
    # deployment. A frozen 70/30 temporal split was tried first and
    # measures mostly bucket-coverage drift (most predictions fall to
    # the heuristic), which is not how the sidecar runs.
    pred = LatencyPredictor()
    warm = len(samples) // 4
    errs = []
    sources: dict[str, int] = {}
    for i, (feats, ttft) in enumerate(samples):
        if i >= warm:
            p, src = pred.predict_ttft(feats)
            sources[src] = sources.get(src, 0) + 1
            errs.append(abs(p - ttft) / max(ttft, 1e-6))
        pred.observe_ttft(feats, ttft)
    return {
        "predictor_ttft_mape": round(float(np.mean(errs)), 4),
        "predictor_ttft_median_ape": round(float(np.median(errs)), 4),
        "n_warmup": warm,
        "n_eval": len(errs),
        "pred_sources": sources,
        "substrate": (
            "real engine trace, prequential eval "
            "(bursty, mixed ISL, prefix hits)"
        ),
    }


def measure_dispatch_rtt_ms() -> float:
    """Median round-trip of a trivial compiled dispatch + host fetch.

    The fetch must be a real device_get: through the axon tunnel a bare
    block_until_ready can return without the result ever crossing the
    wire, reporting ~0 ms."""
    import numpy as np

    import jax
    import jax.numpy as jnp

    f = jax.jit(lambda x: x + 1)
    x = jnp.zeros((8,), jnp.float32)
    np.asarray(jax.device_get(f(x)))
    samples = []
    for _ in range(5):
        t0 = time.monotonic()
        np.asarray(jax.device_get(f(x)))
        samples.append(time.monotonic() - t0)
    samples.sort()
    return samples[len(samples) // 2] * 1e3


def _run_part(part: str):
    """One sub-benchmark (dispatched in a SUBPROCESS by main: engines do
    not share a device arena — a fragmented/lagging reclaim from one
    bench must not RESOURCE_EXHAUST the next on the tunnel-attached
    chip)."""
    if part == "dense_int8":
        tok_s, _, roofline = bench_dense("int8", kv_dtype="bfloat16")
        return {"tok_s": round(tok_s, 1), "roofline": roofline}
    if part == "kv_int8_long":
        return bench_kv_int8_long_context()
    if part == "kv_bf16_long":
        return bench_kv_bf16_long_context()
    if part == "dense_bf16":
        tok_s, stream, roofline = bench_dense(None, kv_dtype="bfloat16")
        return {
            "dense_bf16_tok_s": round(tok_s, 1),
            "weight_stream_gbps": round(stream, 1),
            "roofline_bf16": roofline,
        }
    if part == "mla_moe":
        return round(bench_mla_moe(), 1)
    if part == "pd":
        p50, stages = asyncio.run(_bench_pd_ttft())
        return {"pd_ttft_p50_ms": round(p50, 1), "pd_stages": stages}
    if part == "pd_int8":
        # Same configuration as the r03 number under this key: bf16
        # pools + the opt-in int8 TRANSFER encoding (comparable
        # round-over-round; also keeps the float-pool q8 wire measured).
        p50, stages = asyncio.run(_bench_pd_ttft(transfer_dtype="int8"))
        return {"pd_ttft_p50_int8_ms": round(p50, 1), "pd_int8_stages": stages}
    if part == "pd_kvint8":
        # Int8 POOLS both sides: q8 wire ships pool bytes directly.
        p50, stages = asyncio.run(_bench_pd_ttft(kv_dtype="int8"))
        return {
            "pd_ttft_p50_kvint8_ms": round(p50, 1),
            "pd_kvint8_stages": stages,
        }
    if part == "pd_local":
        # Single-host xPyD device fast path (reference single-host/pd
        # shape): consumer claims the producer's device snapshots — no
        # host staging, no wire.
        p50, stages = asyncio.run(_bench_pd_ttft(local_fastpath=True))
        return {
            "pd_ttft_p50_local_ms": round(p50, 1),
            "pd_local_stages": stages,
        }
    if part == "pd_cached":
        # Byte-diet warm case: repeated prompt -> probe makes the
        # producer stage nothing; near-zero transfer.
        p50, stages = asyncio.run(_bench_pd_ttft(cached_repeat=True))
        return {
            "pd_ttft_p50_cached_ms": round(p50, 1),
            "pd_cached_stages": stages,
        }
    if part == "pd_adaptive":
        # transfer_dtype="adaptive": the producer measures both wire
        # encodings on this link and converges to the faster (VERDICT r4
        # item 8 — r3 and r4 measured OPPOSITE winners on this tunnel,
        # so the right encoding is a link property, not a config).
        p50, stages = asyncio.run(_bench_pd_ttft(transfer_dtype="adaptive"))
        return {
            "pd_ttft_p50_adaptive_ms": round(p50, 1),
            "pd_adaptive": stages,
        }
    if part == "env":
        return bench_env_probes()
    if part == "swa_ring_off":
        return bench_swa_ring(False)
    if part == "swa_ring_on":
        return bench_swa_ring(True)
    if part == "rtt":
        return round(measure_dispatch_rtt_ms(), 1)
    if part == "predictor":
        # Real-engine trace (the honest number); the synthetic eval
        # stays as a generator-consistency check in the extras.
        from llmd_tpu.predictor.synth import run_accuracy_eval

        out = bench_predictor_real()
        out["predictor_synth_mape"] = round(
            run_accuracy_eval()["ttft_mape"], 4
        )
        return out
    if part == "dbo":
        return _bench_dbo_delta()
    if part == "moe_ep":
        return _bench_moe_ep()
    if part == "moe_overlap":
        return _bench_moe_overlap()
    if part == "async_step":
        return bench_async_step()
    if part == "spec_decode":
        return bench_spec_decode()
    if part == "spec_window":
        return bench_spec_window()
    if part == "unified_step":
        return bench_unified_step()
    if part == "ragged_step":
        return bench_ragged_step()
    if part == "fault_degrade":
        return bench_fault_degrade()
    if part == "fleet_soak":
        return bench_fleet_soak()
    if part == "kv_federation":
        return bench_kv_federation()
    if part == "stream_resume":
        return bench_stream_resume()
    if part == "batch_backfill":
        return bench_batch_backfill()
    if part == "lora_pool":
        return bench_lora_pool()
    if part == "pd_stream":
        return bench_pd_stream()
    if part == "long_context":
        return bench_long_context()
    raise KeyError(part)


def bench_pd_stream():
    """Sub-200 ms disaggregated TTFT, CPU-sim part (kv-cache.md
    "layer-streamed import"): the FULL sidecar two-phase P->D stack —
    HTTP proxy, two engines, kvship wire, prefix-cache probe — at a
    CPU-compilable model size, measuring the v3 group-streamed import
    end to end.

    Four legs: streamed local-fastpath (the single-host xPyD shape),
    streamed byte-diet cached repeat, streamed WIRE (group cells over
    TCP loopback with the fetch->CRC->scatter pipeline + first-group
    admission), and the monolithic (stream_groups=1, v2 wire)
    local-fastpath comparison. The local/cached p50s are the < 200 ms
    acceptance record; the waterfall is consecutive monotonic segments
    of the last wire request's timeline, so it provably sums to that
    request's TTFT within clock epsilon — both asserted by the CI
    summary check."""
    import asyncio

    import jax

    jax.config.update("jax_platforms", "cpu")

    from llmd_tpu.config import tiny_model_config

    model = tiny_model_config(num_layers=8, max_model_len=128)
    kw = dict(
        model_cfg=model, isl=96, n_requests=8, page_size=8,
        num_blocks=256,
    )
    local_p50, local_stages = asyncio.run(
        _bench_pd_ttft(local_fastpath=True, **kw)
    )
    cached_p50, cached_stages = asyncio.run(
        _bench_pd_ttft(cached_repeat=True, **kw)
    )
    wire_p50, wire_stages = asyncio.run(_bench_pd_ttft(**kw))
    mono_p50, _mono_stages = asyncio.run(
        _bench_pd_ttft(stream_groups=1, **kw)
    )
    waterfall = wire_stages.get("waterfall", {})
    total = wire_stages.get("waterfall_total_ms", 0.0)
    last = wire_stages.get("last_ttft_ms", 0.0)
    return {
        "substrate": (
            "cpu-sim (tiny geometry; the pd_local/pd_cached chip parts "
            "carry the device-staging numbers)"
        ),
        # The acceptance record: streamed local-fastpath and byte-diet
        # cached p50 TTFT through the full sidecar path.
        "pd_ttft_p50_local_ms": round(local_p50, 1),
        "pd_ttft_p50_cached_ms": round(cached_p50, 1),
        "target_200ms_met": bool(local_p50 < 200 and cached_p50 < 200),
        # The wire pipeline: group cells streamed over TCP loopback.
        "pd_ttft_p50_wire_ms": round(wire_p50, 1),
        "streamed_cells": wire_stages.get("stream_groups_cells", 0),
        "first_group_ms": wire_stages.get("consumer_first_group_ms", 0.0),
        # Fraction of the wire-import window the request was already
        # admitted/schedulable for (first-group admission seam).
        "overlap_ratio": wire_stages.get("overlap_ratio", 0.0),
        # Monolithic (v2, stream_groups=1) WIRE comparison — the leg the
        # stage/ship/fetch pipeline is built for (the local fast path is
        # already device-copy-bound either way).
        "pd_ttft_p50_wire_mono_ms": round(mono_p50, 1),
        "stream_vs_mono_ratio": round(wire_p50 / max(mono_p50, 1e-9), 3),
        # The per-stage waterfall: telescoping segments of ONE request's
        # monotonic timeline — sums to its TTFT within epsilon.
        "waterfall": waterfall,
        "waterfall_total_ms": total,
        "waterfall_ttft_ms": last,
        "waterfall_sums_to_ttft": bool(
            last > 0 and abs(total - last) <= max(5.0, 0.05 * last)
        ),
        "cached_stages": {
            k: v for k, v in cached_stages.items()
            if not isinstance(v, dict)
        },
    }


def bench_fleet_soak():
    """Fleet-scale chaos-soak CPU-sim part (fleet-soak.md): the
    replica-kill and steady scenarios from the seeded matrix at reduced
    scale (~2k QPS, the full >=10^4-QPS matrix runs in the CI `soak`
    job), recording the fleet-level recovery scoreboard headline: zero
    requests lost to the mid-stream crashes, bounded time-to-reroute,
    breaker-open visible, p99 TTFT/TPOT bands — and the determinism
    contract, proven by running the chaos scenario TWICE and comparing
    scoreboard bytes. No chip, no jax: the simulator drives the real
    EPP/flow-control/breaker/predictor/autoscale code on a virtual-time
    event loop, so ~2 s of fleet time costs ~1 s of wall clock."""
    from llmd_tpu.fleetsim.scenarios import SCENARIOS
    from llmd_tpu.fleetsim.scoreboard import to_canonical_json

    scale = 0.2
    t0 = time.monotonic()
    kill_a = SCENARIOS["replica_kill"].build(0, scale).run()
    kill_wall_s = time.monotonic() - t0
    kill_b = SCENARIOS["replica_kill"].build(0, scale).run()
    steady = SCENARIOS["steady"].build(0, scale).run()
    return {
        "qps_scale": scale,
        "deterministic": (
            to_canonical_json(kill_a) == to_canonical_json(kill_b)
        ),
        "zero_lost": (
            kill_a["requests"]["lost"] == 0
            and kill_a["requests"]["hung"] == 0
        ),
        "invariants_ok": bool(kill_a["ok"] and steady["ok"]),
        "replica_kill": {
            "requests": kill_a["trace"]["requests"],
            "offered_qps": round(kill_a["trace"]["offered_qps"], 1),
            "kills": len(kill_a["reroute"]["kills"]),
            "breaker_trips": kill_a["breaker"]["trips_total"],
            "time_to_reroute_s": round(
                kill_a["reroute"]["time_to_reroute_s"], 4
            ),
            "p99_ttft_ms": round(kill_a["latency_ms"]["ttft"]["p99"], 2),
            "stream_interrupted": kill_a["requests"]["outcomes"].get(
                "stream-interrupted", 0
            ),
            "wall_s": round(kill_wall_s, 2),
        },
        "steady": {
            "requests": steady["trace"]["requests"],
            "offered_qps": round(steady["trace"]["offered_qps"], 1),
            "p99_ttft_ms": round(steady["latency_ms"]["ttft"]["p99"], 2),
            "p99_tpot_ms": round(steady["latency_ms"]["tpot"]["p99"], 2),
            "jain_fairness": round(
                steady["fairness"]["jain_completed"], 4
            ),
        },
    }


def bench_kv_federation():
    """Cross-replica KV-federation CPU-sim part (kv-federation.md): the
    kv_federation fleetsim scenario — overlapping-tenant shared
    prefixes, tight per-replica caches, seeded store-leg pull drops —
    run FEDERATED (simulated store tier armed) and COLD (store
    disabled, every shared prefix re-prefills), on the same trace and
    seed. Virtual time is deterministic, so the TTFT comparison is
    exact, not wall-clock noise: the headline is the fraction of
    offered shared-prefix tokens the store erased
    (recompute_avoided_ratio) and the federated-vs-cold p50 TTFT
    ratio. Determinism is proven by running the federated leg twice
    and comparing scoreboard bytes."""
    from llmd_tpu.fleetsim.scenarios import build_kv_federation
    from llmd_tpu.fleetsim.scoreboard import to_canonical_json

    scale = 0.5
    seed = 0
    fed_sim = build_kv_federation(seed, scale, store=True)
    offered_prefix_tokens = sum(r.prefix_tokens for r in fed_sim.trace)
    fed = fed_sim.run()
    fed_b = build_kv_federation(seed, scale, store=True).run()
    cold = build_kv_federation(seed, scale, store=False).run()
    kf = fed["kv_federation"]
    avoided = kf["recompute_avoided_tokens"]
    return {
        "qps_scale": scale,
        "deterministic": (
            to_canonical_json(fed) == to_canonical_json(fed_b)
        ),
        "invariants_ok": bool(fed["ok"] and cold["ok"]),
        "zero_lost": (
            fed["requests"]["lost"] == 0 and cold["requests"]["lost"] == 0
        ),
        "offered_prefix_tokens": offered_prefix_tokens,
        "recompute_avoided_tokens": avoided,
        # the summary-check headline: > 0 means fleet-wide reuse is real
        "recompute_avoided_ratio": round(
            avoided / max(1, offered_prefix_tokens), 4
        ),
        "store": kf["store"],
        "store_published": kf["store_published"],
        "store_hits": kf["store_hits"],
        "local_prefix_hits": kf["local_prefix_hits"],
        "dropped_pulls": kf["store"]["dropped_pulls"],
        "p50_ttft_ms": {
            "federated": round(fed["latency_ms"]["ttft"]["p50"], 2),
            "cold": round(cold["latency_ms"]["ttft"]["p50"], 2),
        },
        # deterministic virtual time: federated prefill must be cheaper
        "ttft_ratio_fed_vs_cold": round(
            fed["latency_ms"]["ttft"]["p50"]
            / max(1e-9, cold["latency_ms"]["ttft"]["p50"]), 4
        ),
    }


def bench_stream_resume():
    """Mid-stream failover CPU-sim part (fault-tolerance.md, stream
    continuation contract): the replica_kill fleetsim scenario — two
    replicas crashed mid-stream with the federation store tier armed —
    at reduced scale. Virtual time is deterministic, so the headline
    comparison is exact: p50 TTFT of resumed legs (store fetch of the
    replayed prefix + tail prefill) vs the deterministic cost of
    recomputing prompt + delivered history cold. Gates: resumes > 0,
    ZERO client-visible stream failures, stitched streams byte-identical
    to the uninterrupted expectation (parity), determinism across two
    runs — plus a router_soak leg driving the REAL epp/server.py aiohttp
    router's proxy/resume path over loopback sockets on the virtual
    loop (content gates only; real I/O is not byte-compared)."""
    from llmd_tpu.fleetsim.scenarios import SCENARIOS
    from llmd_tpu.fleetsim.scoreboard import to_canonical_json

    scale = 0.25
    t0 = time.monotonic()
    a = SCENARIOS["replica_kill"].build(0, scale).run()
    kill_wall_s = time.monotonic() - t0
    b = SCENARIOS["replica_kill"].build(0, scale).run()
    sc = a["stream_continuation"]
    router = SCENARIOS["router_soak"].build(0, 1.0).run()
    rsc = router["stream_continuation"]
    return {
        "qps_scale": scale,
        "deterministic": to_canonical_json(a) == to_canonical_json(b),
        "invariants_ok": bool(a["ok"] and router["ok"]),
        "zero_lost": (
            a["requests"]["lost"] == 0 and a["requests"]["hung"] == 0
        ),
        "kills": len(a["reroute"]["kills"]),
        "mid_stream_failures": sc["mid_stream_failures"],
        "resumes": sc["resumes"],
        "resume_replayed_tokens": sc["resume_replayed_tokens"],
        # THE acceptance gates: nothing client-visible, streams whole.
        "client_visible_stream_failures": (
            sc["interrupted"]
            + a["requests"]["outcomes"].get("stream-corrupt", 0)
        ),
        "parity_failures": sc["parity_failures"],
        # kill-at-p50 headline: resume TTFT must be store-fetch-bound,
        # not recompute-bound.
        "resume_ttft_p50_ms": round(sc["resume_ttft_p50_ms"], 3),
        "cold_recompute_ttft_p50_ms": round(
            sc["cold_recompute_ttft_p50_ms"], 3
        ),
        "resume_vs_cold_ratio": round(
            sc["resume_ttft_p50_ms"]
            / max(1e-9, sc["cold_recompute_ttft_p50_ms"]), 4
        ),
        "wall_s": round(kill_wall_s, 2),
        # The REAL router leg: the production proxy detected the cuts,
        # fed the breaker, and replayed the history end to end.
        "router_soak": {
            "requests": router["trace"]["requests"],
            "kills": len(router["reroute"]["kills"]),
            "mid_stream_failures": rsc["mid_stream_failures"],
            "resumes": rsc["resumes"],
            "parity_failures": rsc["parity_failures"],
            "client_visible_stream_failures": rsc["interrupted"],
            "invariants_ok": bool(router["ok"]),
        },
    }


def bench_long_context():
    """Million-token context tier CPU-sim part (long-context.md).

    ENGINE leg — a real LLMEngine on the 8-device virtual CPU mesh:
    TTFT at growing context lengths for cp=1 vs cp=2 ring prefill (a
    warm-up prompt per bucket excludes compile; CPU wall-clock is
    recorded as context, the GATES are structural — ring steps > 0 and
    greedy-token parity), plus resident-KV-bytes-per-seq with the
    decode-time pager on vs off over the same long decode (the pager
    leg must spill and stay bounded near window + horizon while the
    off leg's residency tracks full context).

    FLEET leg — the long_context fleetsim scenario at reduced scale,
    cp on vs off on the same seeded trace: virtual time is
    deterministic, so the document-TTFT compression is exact (~the cp
    degree), with the chat-p99-through-the-wave and kv-peak-bounded
    gates riding along."""
    import os

    flags = os.environ.get("XLA_FLAGS", "")
    if "host_platform_device_count" not in flags:
        os.environ["XLA_FLAGS"] = (
            f"{flags} --xla_force_host_platform_device_count=8".strip()
        )
    import jax

    jax.config.update("jax_platforms", "cpu")
    import numpy as np

    from llmd_tpu.config import (
        CacheConfig,
        EngineConfig,
        OffloadConfig,
        ParallelConfig,
        SchedulerConfig,
        tiny_model_config,
    )
    from llmd_tpu.engine.engine import LLMEngine
    from llmd_tpu.engine.request import SamplingParams

    rng = np.random.default_rng(0)

    def make(cp=0, window=0, paging=False):
        dp = cp if cp else 1
        return LLMEngine(EngineConfig(
            model=tiny_model_config(max_model_len=512, sliding_window=window),
            cache=CacheConfig(page_size=4, num_blocks=256, dtype="float32"),
            scheduler=SchedulerConfig(
                max_num_seqs=8, max_num_batched_tokens=256,
            ),
            parallel=ParallelConfig(
                tensor_parallel_size=1, data_parallel_size=dp,
                cp_prefill=cp if cp else 1, cp_prefill_min_tokens=16,
            ),
            offload=OffloadConfig(
                enabled=True, cpu_chunks=512, decode_paging=True,
                pager_horizon_tokens=8,
            ) if paging else None,
            seed=0,
        ))

    # --- TTFT vs context length, cp=1 vs cp=2 (ring prefill) ---------- #
    one_tok = SamplingParams(temperature=0.0, max_tokens=1)
    ctx_lengths = (128, 256)
    ttft_ms: dict = {}
    tokens: dict = {}
    ring_steps = 0
    for cp in (0, 2):
        eng = make(cp=cp)
        rows = {}
        for ctx in ctx_lengths:
            # Warm-up compiles this Q bucket; the timed prompt differs
            # in content so the prefix cache cannot skip the prefill.
            warm = list(rng.integers(0, 256, size=ctx))
            eng.generate([warm], one_tok)
            timed = list(
                np.random.default_rng(ctx).integers(0, 256, size=ctx)
            )
            t0 = time.monotonic()
            out = eng.generate([timed], one_tok)
            rows[str(ctx)] = round((time.monotonic() - t0) * 1e3, 2)
            tokens.setdefault(str(ctx), {})[f"cp{cp or 1}"] = (
                list(out.values())[0]
            )
        ttft_ms[f"cp{cp or 1}"] = rows
        if cp:
            ring_steps = eng.runner.cp_ring_steps_total
    parity = all(
        tokens[str(ctx)]["cp1"] == tokens[str(ctx)]["cp2"]
        for ctx in ctx_lengths
    )

    # --- resident KV bytes per sequence, pager on vs off -------------- #
    prompt = list(rng.integers(0, 256, size=48))
    decode = SamplingParams(temperature=0.0, max_tokens=40)
    page_bytes = None
    resident: dict = {}
    for paging in (False, True):
        eng = make(window=8, paging=paging)
        if page_bytes is None:
            page_bytes = int(eng.runner.gather_pages([0]).nbytes)
        rid = eng.add_request(prompt, decode)
        peak_pages = 0
        for _ in range(200):
            if not eng.has_work():
                break
            eng.step()
            for req in eng.scheduler.running:
                if req.request_id == rid:
                    peak_pages = max(
                        peak_pages,
                        len(req.block_ids) - len(getattr(
                            req, "paged_out", {},
                        )),
                    )
        key = "pager_on" if paging else "pager_off"
        resident[key] = {
            "peak_resident_pages": peak_pages,
            "peak_resident_kv_bytes": peak_pages * page_bytes,
        }
        if paging:
            resident[key]["kv_paged_out_bytes"] = int(
                eng.pager.paged_out_bytes
            )

    # --- the fleet leg: exact virtual-time document-TTFT scaling ------ #
    from llmd_tpu.fleetsim.scenarios import build_long_context

    scale = 0.25
    on = build_long_context(0, scale).run()
    off = build_long_context(0, scale, cp=False).run()
    doc_on = on["per_tenant"]["docs"]["p99_ttft_ms"]
    doc_off = off["per_tenant"]["docs"]["p99_ttft_ms"]
    return {
        "engine": {
            "ttft_ms": ttft_ms,
            "cp_ring_steps": ring_steps,
            "cp_token_parity": parity,
            "page_bytes": page_bytes,
            "resident_kv": resident,
        },
        "fleet": {
            "qps_scale": scale,
            "cp_degree": on["long_context"]["cp_degree"],
            "doc_ttft_p99_ms_cp": round(doc_on, 1),
            "doc_ttft_p99_ms_mono": round(doc_off, 1),
            # THE headline: ring prefill compresses document TTFT by
            # ~the cp degree, exactly, in virtual time.
            "doc_ttft_speedup": round(doc_off / max(doc_on, 1e-9), 2),
            "chat_p99_ttft_ms": round(max(
                v["p99_ttft_ms"]
                for t, v in on["per_tenant"].items() if t != "docs"
            ), 2),
            "kv_paged_out_tokens": on["long_context"]["kv_paged_out_tokens"],
            "peak_kv_tokens": on["long_context"]["peak_kv_tokens"],
            "kv_capacity_tokens": on["long_context"]["kv_capacity_tokens"],
            "invariants_ok": bool(on["ok"] and off["ok"]),
        },
    }


def bench_batch_backfill():
    """Batch serving tier CPU-sim part (batch-processing.md): the
    batch_backfill fleetsim scenario run BATCH-ON (standing offline
    queue at BATCH_PRIORITY riding the real flow-control band, the
    production chain's batch-saturation-filter, and the replicas'
    backfill path, with the WVA flooring the fleet on the backlog) and
    NO-BATCH (same diurnal interactive trace, utilization sampler
    armed) — virtual time, so the comparison is exact. Headlines: batch
    tok/s harvested from trough capacity, the trough-utilization lift
    over the no-batch baseline, backlog drained to zero, and the
    interactive p99 TTFT on/off ratio — the zero-interactive-regression
    bar the CI summary asserts. Determinism proven by running the
    batch-on leg twice and comparing scoreboard bytes."""
    from llmd_tpu.fleetsim.scenarios import build_batch_backfill
    from llmd_tpu.fleetsim.scoreboard import to_canonical_json

    scale = 0.5
    seed = 0
    t0 = time.monotonic()
    on = build_batch_backfill(seed, scale, batch=True).run()
    wall_s = time.monotonic() - t0
    on_b = build_batch_backfill(seed, scale, batch=True).run()
    off = build_batch_backfill(seed, scale, batch=False).run()
    bt = on["batch"]
    # Harvested-token rate over the window the jobs actually drained in
    # (virtual seconds — the deterministic "batch tok/s" headline).
    drain_span = max(bt["last_drain_t"], 1e-9)
    p99_on = on["latency_ms"]["ttft"]["p99"]
    p99_off = off["latency_ms"]["ttft"]["p99"]
    return {
        "qps_scale": scale,
        "deterministic": (
            to_canonical_json(on) == to_canonical_json(on_b)
        ),
        "invariants_ok": bool(on["ok"] and off["ok"]),
        "zero_lost": (
            on["requests"]["lost"] == 0 and on["requests"]["hung"] == 0
        ),
        "jobs": bt["enqueued"],
        "backlog_drained": bt["outstanding"] == 0 and bt["hung"] == 0,
        "backlog_monotone": bt["backlog_monotone_after_peak"],
        "watermark_retries": bt["retries"],
        "harvested_tokens": bt["harvested_tokens"],
        "batch_tok_s_harvested": round(
            bt["harvested_tokens"] / drain_span, 1
        ),
        "trough_utilization": {
            "batch_on": round(
                on["utilization"]["trough_utilization"], 4
            ),
            "no_batch": round(
                off["utilization"]["trough_utilization"], 4
            ),
        },
        "interactive_p99_ttft_ms": {
            "batch_on": round(p99_on, 2),
            "no_batch": round(p99_off, 2),
        },
        # the summary-check headline: backfill must cost interactive
        # latency nothing (ratio ~1.0 in exact virtual time)
        "p99_ratio_on_vs_off": round(p99_on / max(1e-9, p99_off), 4),
        "wall_s": round(wall_s, 2),
    }


def bench_lora_pool():
    """Multi-tenant LoRA CPU-sim part (multi-tenant-lora.md): two legs.

    ENGINE leg — a real engine with a 2-slot paged adapter pool over a
    6-tenant registry serves a mixed-tenant round-robin workload (every
    request a different tenant: worst-case churn) vs the same request
    count on ONE adapter (all-resident baseline); headline is the
    throughput ratio and the cold-vs-resident first-request latency
    ratio (both recorded, not asserted — CPU wall clock is noisy),
    plus the cold-load/eviction counts and resident-vs-cold byte
    parity (greedy + seeded) — the CI summary check asserts those.

    FLEET leg — the lora_tenant fleetsim scenario (192 Zipf tenants,
    32-slot pools) run affinity-routed vs adapter-blind on the same
    trace; virtual time, so the resident-hit-ratio lift and cold-stall
    comparison are exact. Determinism proven by running the affinity
    leg twice and comparing scoreboard bytes."""
    import jax

    jax.config.update("jax_platforms", "cpu")

    import numpy as np

    from llmd_tpu.config import (
        CacheConfig, EngineConfig, SchedulerConfig, tiny_model_config,
    )
    from llmd_tpu.engine import LLMEngine, SamplingParams

    N_REQ, ISL, OSL, TENANTS = 18, 16, 8, 6

    def make_engine():
        return LLMEngine(EngineConfig(
            model=tiny_model_config(
                name="tiny-lora", num_lora_adapters=2, lora_rank=4,
                lora_dynamic=True,
            ),
            cache=CacheConfig(page_size=4, num_blocks=256, dtype="float32"),
            scheduler=SchedulerConfig(
                max_num_seqs=8, max_num_batched_tokens=64
            ),
            seed=0,
        ))

    def adapter_weights(engine, seed):
        layers = engine.runner.params["layers"]
        rng = np.random.default_rng(seed)
        return {
            k: rng.normal(
                0.0, 0.5, (layers[k].shape[0], *layers[k].shape[2:])
            ).astype(np.float32)
            for k in ("la_q", "lb_q", "la_v", "lb_v")
        }

    names = [f"tenant-{i}" for i in range(TENANTS)]

    def run_one(eng, name, seed=None, prompt=None):
        rid = eng.add_request(
            prompt or list(range(2, 2 + ISL)),
            SamplingParams(
                temperature=0.0 if seed is None else 0.8,
                max_tokens=OSL, ignore_eos=True, seed=seed,
            ),
            lora_name=name,
        )
        outs = []
        while eng.has_work():
            for out in eng.step():
                if out.request_id == rid:
                    outs.extend(out.new_token_ids)
        return outs

    def leg(mixed: bool) -> dict:
        eng = make_engine()
        for i, n in enumerate(names):
            eng.load_adapter(n, weights=adapter_weights(eng, 100 + i))
        run_one(eng, names[0])  # warm the step shapes off the clock
        t0 = time.monotonic()
        for i in range(N_REQ):
            run_one(eng, names[i % TENANTS] if mixed else names[0])
        dt = time.monotonic() - t0
        pc = eng.adapter_pool.counters()
        return {"tok_s": N_REQ * OSL / dt, **pc}

    single = leg(mixed=False)
    mixed = leg(mixed=True)

    # Cold-vs-resident TTFT ratio + byte parity: engine A serves the
    # adapter resident; engine B must first evict it, then cold-load it
    # back for the timed request. Same weights, byte-identical streams.
    streams = {}
    lat = {}
    for mode in ("resident", "cold"):
        eng = make_engine()
        eng.load_adapter("x", weights=adapter_weights(eng, 7))
        run_one(eng, "x")  # warm shapes + make x resident
        if mode == "cold":
            eng.load_adapter("y", weights=adapter_weights(eng, 8))
            eng.load_adapter("z", weights=adapter_weights(eng, 9))
            run_one(eng, "y")
            run_one(eng, "z")
            assert eng.adapter_pool.slot_of("x") is None
        t0 = time.monotonic()
        greedy = run_one(eng, "x", prompt=list(range(3, 3 + ISL)))
        lat[mode] = time.monotonic() - t0
        seeded = run_one(eng, "x", seed=1234, prompt=list(range(3, 3 + ISL)))
        streams[mode] = (greedy, seeded)

    from llmd_tpu.fleetsim.scenarios import build_lora_tenant
    from llmd_tpu.fleetsim.scoreboard import to_canonical_json

    scale = 0.5
    aff = build_lora_tenant(0, scale, affinity=True).run()
    aff_b = build_lora_tenant(0, scale, affinity=True).run()
    blind = build_lora_tenant(0, scale, affinity=False).run()
    return {
        "engine": {
            "tenants": TENANTS,
            "pool_slots": 2,
            "single_adapter_tok_s": round(single["tok_s"], 1),
            "mixed_tenant_tok_s": round(mixed["tok_s"], 1),
            # worst-case churn cost (recorded; CPU wall clock is noisy)
            "mixed_vs_single_ratio": round(
                mixed["tok_s"] / max(single["tok_s"], 1e-9), 3
            ),
            "cold_loads": mixed["cold_loads"],
            "evictions": mixed["evictions"],
            "cold_ttft_ms": round(lat["cold"] * 1e3, 1),
            "resident_ttft_ms": round(lat["resident"] * 1e3, 1),
            "cold_ttft_ratio": round(
                lat["cold"] / max(lat["resident"], 1e-9), 3
            ),
            # THE parity bar: resident and cold-loaded streams are
            # byte-identical, greedy and seeded.
            "outputs_identical": streams["resident"] == streams["cold"],
        },
        "fleet": {
            "qps_scale": scale,
            "deterministic": (
                to_canonical_json(aff) == to_canonical_json(aff_b)
            ),
            "invariants_ok": bool(aff["ok"] and blind["ok"]),
            "zero_lost": (
                aff["requests"]["lost"] == 0
                and aff["requests"]["hung"] == 0
            ),
            "adapters": aff["lora"]["adapters"],
            "affinity_hit_ratio": round(aff["lora"]["hit_ratio"], 4),
            "blind_hit_ratio": round(blind["lora"]["hit_ratio"], 4),
            # exact virtual-time lift of residency-aware routing
            "hit_ratio_lift": round(
                aff["lora"]["hit_ratio"]
                / max(blind["lora"]["hit_ratio"], 1e-9), 4
            ),
            "cold_loads": aff["lora"]["cold_loads"],
            "evictions": aff["lora"]["evictions"],
            "pinned_evictions": aff["lora"]["pinned_evictions"],
            "cold_stall_p50_ms": round(
                aff["lora"]["cold_stall_p50_ms"], 2
            ),
        },
    }


def bench_fault_degrade():
    """Graceful-degradation CPU-sim part (fault-tolerance.md): P/D
    engine pair serving a stream of unique prompts, once clean and once
    under a seeded 1%-kv.pull.drop FaultPlan (plus one guaranteed drop,
    so the recompute path provably engages even at small N). Dropped
    pulls degrade to local recompute — correct but slower — and the
    headline is the throughput RATIO under faults vs clean: the
    target is >= 0.9 (degradation must cost single-digit percent at a
    1% drop rate, not collapse the consumer). Streams are asserted
    byte-identical per prompt across the two legs: degradation is
    TRANSPARENT, not just survivable."""
    import jax

    jax.config.update("jax_platforms", "cpu")

    from llmd_tpu import faults
    from llmd_tpu.config import (
        CacheConfig, EngineConfig, ParallelConfig, SchedulerConfig,
        tiny_model_config,
    )
    from llmd_tpu.engine import LLMEngine, SamplingParams

    N, ISL, OSL = 24, 18, 8
    model = tiny_model_config()

    def make_engine(kv_role):
        return LLMEngine(EngineConfig(
            model=model,
            cache=CacheConfig(page_size=4, num_blocks=256, dtype="float32"),
            scheduler=SchedulerConfig(
                max_num_seqs=8, max_num_batched_tokens=64
            ),
            parallel=ParallelConfig(tensor_parallel_size=1),
            seed=0,
            kv_role=kv_role,
            kv_transfer_port=0,
            kv_local_fastpath=False,  # the faults live on the wire path
        ))

    # Unique prompts so every request really pulls (a shared prefix
    # would let the consumer's cache absorb the drops for free).
    prompts = [
        [((i * 7 + j) % (model.vocab_size - 2)) + 2 for j in range(ISL)]
        for i in range(N)
    ]

    def run_one(eng, prompt, max_tokens, kv_params=None):
        rid = eng.add_request(
            list(prompt),
            SamplingParams(
                temperature=0.0, max_tokens=max_tokens, ignore_eos=True
            ),
            kv_transfer_params=kv_params,
        )
        outs, final = [], None
        while eng.has_work():
            for out in eng.step():
                if out.request_id == rid:
                    outs.extend(out.new_token_ids)
                    if out.finished:
                        final = out
        return outs, final

    def leg(armed: bool) -> dict:
        producer = make_engine("kv_producer")
        consumer = make_engine("kv_consumer")
        try:
            if armed:
                faults.arm(faults.FaultPlan([
                    faults.FaultSpec(
                        site="kv.pull.drop", p=0.01, times=None
                    ),
                    faults.FaultSpec(site="kv.pull.drop", times=1),
                ], seed=7))
            else:
                faults.disarm()
            # warm both engines' step shapes off the clock
            run_one(producer, prompts[0], 1)
            run_one(consumer, prompts[0], 2)
            toks = 0
            streams = []
            t0 = time.monotonic()
            for prompt in prompts:
                _, pre = run_one(
                    producer, prompt, 1,
                    kv_params={"do_remote_decode": True},
                )
                outs, _ = run_one(
                    consumer, prompt, OSL, kv_params=pre.kv_transfer_params
                )
                toks += len(outs)
                streams.append(outs)
            dt = time.monotonic() - t0
            return {
                "tok_s": toks / dt,
                "streams": streams,
                "recompute_fallbacks":
                    consumer.kv_connector.recompute_fallbacks,
                "drops": faults.injected_counts().get("kv.pull.drop", 0),
            }
        finally:
            faults.disarm()
            producer.kv_connector.close()
            consumer.kv_connector.close()

    clean = leg(False)
    faulty = leg(True)
    ratio = faulty["tok_s"] / max(clean["tok_s"], 1e-9)
    return {
        "clean_tok_s": round(clean["tok_s"], 1),
        "faulty_tok_s": round(faulty["tok_s"], 1),
        # The headline: throughput under a 1% pull-drop plan relative
        # to the clean run (target >= 0.9; CPU-sim wall clock is noisy,
        # so the target is recorded, not hard-asserted here).
        "degrade_ratio": round(ratio, 3),
        "target_met": ratio >= 0.9,
        "drops_injected": faulty["drops"],
        "recompute_fallbacks": faulty["recompute_fallbacks"],
        # Degradation transparency: byte-identical greedy streams.
        "outputs_identical": clean["streams"] == faulty["streams"],
        "requests": N,
    }


def bench_ragged_step():
    """Flattened-token step (SchedulerConfig.ragged_qlens) CPU-sim
    microbench: the same rolling mixed prefill+decode workload as
    bench_unified_step, ragged on vs off in LOCKSTEP — same arrivals,
    same scheduler decisions, byte-identical greedy AND seeded streams
    asserted. The headline is the MIXED-BATCH PADDED/LIVE TOKEN RATIO:
    the bucketed unified program pads every decode row to the chunk
    sub-row Q bucket (so a mixed step pays rows x Q_bucket compute for
    sum-of-real-tokens work), while the flat stream pads only to the
    16-token T granule — expect <= 0.15 for the flat path against
    multiples of it for the bucketed one. Wall-clock on the CPU sim is
    NOT the transferable number (the tiny model is compute-bound either
    way); the padding ratio is, because pad lanes ride through every
    layer of the real model too."""
    import jax

    jax.config.update("jax_platforms", "cpu")
    import numpy as np

    from llmd_tpu.config import (
        CacheConfig, EngineConfig, ParallelConfig, SchedulerConfig,
        tiny_model_config,
    )
    from llmd_tpu.engine import LLMEngine, SamplingParams

    SEQS, BUDGET, ISL, OSL, N = 8, 96, 64, 24, 20
    model = tiny_model_config(max_model_len=256)

    def make_engine(ragged: bool) -> LLMEngine:
        cfg = EngineConfig(
            model=model,
            cache=CacheConfig(page_size=4, num_blocks=512, dtype="float32"),
            scheduler=SchedulerConfig(
                max_num_seqs=SEQS, max_num_batched_tokens=BUDGET,
                unified_step=True, ragged_qlens=ragged,
            ),
            parallel=ParallelConfig(tensor_parallel_size=1),
            seed=0,
        )
        return LLMEngine(cfg)

    rng = np.random.default_rng(0)
    prompts = [
        list(rng.integers(1, model.vocab_size, size=ISL)) for _ in range(N)
    ]
    # Half greedy, half seeded: BOTH stream classes must be
    # byte-identical across the ragged switch (unseeded hot sampling is
    # reproducible within a mode only, the standing contract).
    sps = [
        SamplingParams(temperature=0.0, max_tokens=OSL, ignore_eos=True)
        if i % 2 == 0 else
        SamplingParams(
            temperature=0.8, max_tokens=OSL, seed=100 + i, ignore_eos=True
        )
        for i in range(N)
    ]
    engines = {False: make_engine(False), True: make_engine(True)}
    for eng in engines.values():  # warm the step shapes
        eng.generate(
            [list(p) for p in prompts[:SEQS]], [sps[i] for i in range(SEQS)]
        )
    for eng in engines.values():
        st = eng.stats
        st.live_tokens_total = eng.runner.live_tokens_total = 0
        st.padded_tokens_total = eng.runner.padded_tokens_total = 0
        st.step_dispatches_total = 0
        st.engine_steps_total = 0
        st.generation_tokens = 0
    outs: dict[bool, dict[str, list[int]]] = {False: {}, True: {}}
    # Per-step (live, padded) deltas, lockstep across engines: step t of
    # one IS step t of the other, so the mixed-step filter below selects
    # the same steps on both sides.
    deltas: dict[bool, list[tuple[int, int]]] = {False: [], True: []}
    submitted = SEQS
    for eng in engines.values():
        for i in range(SEQS):
            eng.add_request(list(prompts[i]), sps[i])
    while any(eng.has_work() for eng in engines.values()):
        finished = 0
        for ragged, eng in engines.items():
            r = eng.runner
            before = (r.live_tokens_total, r.padded_tokens_total)
            for out in eng.step():
                outs[ragged].setdefault(out.request_id, []).extend(
                    out.new_token_ids
                )
                finished += int(out.finished)
            deltas[ragged].append((
                r.live_tokens_total - before[0],
                r.padded_tokens_total - before[1],
            ))
        for _ in range(min(finished // 2, N - submitted)):
            for eng in engines.values():
                eng.add_request(list(prompts[submitted]), sps[submitted])
            submitted += 1
    streams = {
        u: [outs[u][k] for k in sorted(outs[u])] for u in (False, True)
    }
    identical = streams[False] == streams[True]

    def ratio(ragged: bool, steps) -> float:
        live = sum(deltas[ragged][i][0] for i in steps)
        padded = sum(deltas[ragged][i][1] for i in steps)
        return round(padded / max(live, 1), 4)

    # Mixed steps: more live tokens than a pure-decode step could carry
    # (every decode row contributes at most 1 + spec_k; spec is off
    # here, so > SEQS live tokens means prefill chunks were aboard).
    n = min(len(deltas[False]), len(deltas[True]))
    mixed = [i for i in range(n) if deltas[False][i][0] > SEQS]
    mixed_ratio = {
        "bucketed": ratio(False, mixed), "ragged": ratio(True, mixed)
    }
    overall_ratio = {
        "bucketed": ratio(False, range(n)), "ragged": ratio(True, range(n))
    }
    return {
        "mixed_steps": len(mixed),
        "steps": n,
        # THE acceptance numbers: flat strictly below bucketed, and at
        # or under the 0.15 waste target on mixed batches.
        "mixed_padding_ratio": mixed_ratio,
        "overall_padding_ratio": overall_ratio,
        "padding_bound_ok": bool(
            mixed_ratio["ragged"] < mixed_ratio["bucketed"]
            and mixed_ratio["ragged"] <= 0.15
        ),
        "outputs_identical": identical,
        "dispatches_per_step": {
            ragged: round(
                engines[ragged].stats.step_dispatches_total
                / max(engines[ragged].stats.engine_steps_total, 1), 4
            )
            for ragged in (False, True)
        },
        "window1_shape_families": {
            ragged: engines[ragged].runner.window1_shape_families()
            for ragged in (False, True)
        },
        "substrate": (
            "tiny model on CPU (compute-bound): padding ratios, "
            "outputs_identical and the shape-family counts are the "
            "transferable numbers — pad lanes ride through every layer "
            "of the real model too"
        ),
    }


def bench_unified_step():
    """Unified single-dispatch engine step (SchedulerConfig.unified_step)
    CPU-sim microbench: a rolling mixed prefill+decode workload (chunked
    prompts arriving while a decode pool runs, so nearly every step
    carries both prefill chunks and decode rows), unified on vs off in
    LOCKSTEP — same arrivals, same scheduler decisions, byte-identical
    outputs asserted. The headline is the MIXED-STEP DISPATCH RATIO:
    device programs dispatched on mixed steps, unified / split (expect
    <= 0.6 — the split engine launches a prefill program AND a decode
    program, plus one lockstep opcode broadcast each on multi-host,
    where the unified engine launches one). Also records overall
    dispatches/step and the mean per-step host gap. On a remote-dispatch
    TPU runtime each saved dispatch is a saved host round-trip; the CPU
    sim is compute-bound, so wall-clock here understates the win."""
    import jax

    jax.config.update("jax_platforms", "cpu")
    import numpy as np

    from llmd_tpu.config import (
        CacheConfig, EngineConfig, ParallelConfig, SchedulerConfig,
        tiny_model_config,
    )
    from llmd_tpu.engine import LLMEngine, SamplingParams

    SEQS, BUDGET, ISL, OSL, N = 6, 24, 48, 24, 18
    model = tiny_model_config(max_model_len=128)

    def make_engine(unified: bool) -> LLMEngine:
        cfg = EngineConfig(
            model=model,
            cache=CacheConfig(page_size=4, num_blocks=256, dtype="float32"),
            scheduler=SchedulerConfig(
                max_num_seqs=SEQS, max_num_batched_tokens=BUDGET,
                unified_step=unified,
                # Pin the BUCKETED unified program: ragged_qlens defaults
                # on and would silently swap _OP_FLAT in — that family
                # has its own part (bench_ragged_step); this one must
                # keep covering _OP_UNIFIED, still the live path for MLA
                # models and --no-ragged-qlens.
                ragged_qlens=False,
            ),
            parallel=ParallelConfig(tensor_parallel_size=1),
            seed=0,
        )
        return LLMEngine(cfg)

    rng = np.random.default_rng(0)
    prompts = [
        list(rng.integers(1, model.vocab_size, size=ISL)) for _ in range(N)
    ]
    sp = SamplingParams(temperature=0.0, max_tokens=OSL, ignore_eos=True)
    engines = {False: make_engine(False), True: make_engine(True)}
    for eng in engines.values():  # warm the step shapes (incl. unified)
        eng.generate([list(p) for p in prompts[:SEQS]], sp)
    for eng in engines.values():
        st = eng.stats
        st.step_dispatches_total = 0
        st.engine_steps_total = 0
        st.unified_steps_total = 0
        st.step_host_gap_ms_total = 0.0
        st.generation_tokens = 0
    # LOCKSTEP drive: both engines see the identical arrival schedule
    # (initial pool + one fresh prompt per finish), so step t of one IS
    # step t of the other and per-step dispatch deltas compare directly.
    outs: dict[bool, dict[str, list[int]]] = {False: {}, True: {}}
    deltas: dict[bool, list[int]] = {False: [], True: []}
    submitted = SEQS
    for eng in engines.values():
        for p in prompts[:SEQS]:
            eng.add_request(list(p), sp)
    wall: dict[bool, float] = {False: 0.0, True: 0.0}
    while any(eng.has_work() for eng in engines.values()):
        finished = 0
        for unified, eng in engines.items():
            before = eng.stats.step_dispatches_total
            t = time.monotonic()
            for out in eng.step():
                outs[unified].setdefault(out.request_id, []).extend(
                    out.new_token_ids
                )
                finished += int(out.finished)
            wall[unified] += time.monotonic() - t
            deltas[unified].append(eng.stats.step_dispatches_total - before)
        # One fresh arrival per finished request (arrivals mirrored to
        # both engines keep the drive lockstep); /2 because both engines
        # finish the same request on the same step.
        for _ in range(min(finished // 2, N - submitted)):
            for eng in engines.values():
                eng.add_request(list(prompts[submitted]), sp)
            submitted += 1
    streams = {
        u: [outs[u][k] for k in sorted(outs[u])] for u in (False, True)
    }
    identical = streams[False] == streams[True]
    # Mixed steps: the steps where the SPLIT engine needed >1 program.
    mixed = [i for i, d in enumerate(deltas[False]) if d > 1]
    mixed_split = sum(deltas[False][i] for i in mixed)
    mixed_uni = sum(deltas[True][i] for i in mixed if i < len(deltas[True]))

    def summarize(unified: bool) -> dict:
        st = engines[unified].stats
        return {
            "dispatches_per_step": round(
                st.step_dispatches_total / max(st.engine_steps_total, 1), 4
            ),
            "host_gap_ms_mean": round(
                st.step_host_gap_ms_total / max(st.engine_steps_total, 1), 3
            ),
            "steps": st.engine_steps_total,
            "tok_s": round(st.generation_tokens / max(wall[unified], 1e-9), 1),
            **(
                {"unified_steps": st.unified_steps_total} if unified else {}
            ),
        }

    return {
        "split": summarize(False),
        "unified": summarize(True),
        "mixed_steps": len(mixed),
        # THE acceptance number: device programs on mixed steps,
        # unified / split (expect <= 0.6).
        "mixed_dispatch_ratio": round(mixed_uni / max(mixed_split, 1), 3),
        "outputs_identical": identical,
        "substrate": (
            "tiny model on CPU (compute-bound): mixed_dispatch_ratio and "
            "outputs_identical are the transferable numbers — on an "
            "RTT-dominated TPU runtime each saved dispatch is a saved "
            "host round-trip"
        ),
    }


def bench_async_step():
    """Async stepping (SchedulerConfig.async_scheduling) host-gap
    microbench on the CPU substrate (chip-free: the host gap is a HOST
    property — schedule + page-table build + array prep + assembly — so
    the hidden-vs-exposed comparison carries; absolute tok/s here is a
    tiny-model artifact). Same decode-heavy workload, async off vs on:
    records tok/s, the mean per-step host gap (step_host_gap_ms_total /
    engine_steps_total — un-overlapped host time, exposed every step in
    sync mode, shrunk to the reconcile/patch sliver in async mode), and
    the late-finish rollback count (docs/architecture/
    async-scheduling.md)."""
    import jax

    jax.config.update("jax_platforms", "cpu")
    import numpy as np

    from llmd_tpu.config import (
        CacheConfig, EngineConfig, ParallelConfig, SchedulerConfig,
        tiny_model_config,
    )
    from llmd_tpu.engine import LLMEngine, SamplingParams

    B, ISL, OSL = 16, 64, 48
    model = tiny_model_config(max_model_len=256)

    def run(async_mode: bool) -> dict:
        cfg = EngineConfig(
            model=model,
            cache=CacheConfig(page_size=16, num_blocks=512, dtype="float32"),
            scheduler=SchedulerConfig(
                max_num_seqs=B, max_num_batched_tokens=B * ISL,
                decode_window=1, async_scheduling=async_mode,
            ),
            parallel=ParallelConfig(tensor_parallel_size=1),
            seed=0,
        )
        engine = LLMEngine(cfg)
        rng = np.random.default_rng(0)
        sp = SamplingParams(temperature=0.0, max_tokens=OSL, ignore_eos=True)
        mk = lambda: [  # noqa: E731
            list(rng.integers(1, model.vocab_size, size=ISL)) for _ in range(B)
        ]
        engine.generate(mk(), sp)  # warm the step shapes
        engine.stats.step_host_gap_ms_total = 0.0
        engine.stats.engine_steps_total = 0
        engine.stats.async_rollbacks_total = 0
        t0 = time.monotonic()
        out = engine.generate(mk(), sp)
        dt = time.monotonic() - t0
        total = sum(len(v) for v in out.values())
        assert total == B * OSL, (total, B * OSL)
        st = engine.stats
        res = {
            "tok_s": round(total / dt, 1),
            "host_gap_ms_mean": round(
                st.step_host_gap_ms_total / max(st.engine_steps_total, 1), 3
            ),
            "steps": st.engine_steps_total,
        }
        if async_mode:
            res["rollbacks"] = st.async_rollbacks_total
        return res

    off, on = run(False), run(True)
    return {
        "async_off": off,
        "async_on": on,
        "host_gap_hidden_ratio": round(
            1.0 - on["host_gap_ms_mean"] / max(off["host_gap_ms_mean"], 1e-9),
            3,
        ),
        "substrate": (
            "tiny model on CPU; the gap ratio (not tok/s) is the "
            "transferable number"
        ),
    }


def bench_spec_decode():
    """Speculative decoding (SchedulerConfig.speculative_ngram) CPU-sim
    microbench: n-gram prompt-lookup drafting + one-pass verification,
    spec on/off over two workloads. ``repetitive`` (periodic prompts,
    greedy decode — greedy tiny-model outputs loop, the prompt-lookup
    sweet spot) records MEAN EMITTED TOKENS PER ROW-STEP (the
    transferable number: on a memory-bound TPU decode, tokens/step IS
    the speedup; the CPU sim is compute-bound, so wall-clock here
    UNDERSTATES the win) and the draft acceptance rate. ``adversarial``
    (random prompts, temperature sampling — incompressible output, no
    n-gram ever accepted) pins the overhead of speculation that never
    fires: proposer scans + draft-backoff bookkeeping, which must stay
    within noise of the spec-off engine
    (docs/architecture/speculative-decoding.md)."""
    import jax

    jax.config.update("jax_platforms", "cpu")
    import statistics

    import numpy as np

    from llmd_tpu.config import (
        CacheConfig, EngineConfig, ParallelConfig, SchedulerConfig,
        tiny_model_config,
    )
    from llmd_tpu.engine import LLMEngine, SamplingParams

    B, ISL, OSL, K = 16, 64, 64, 4
    model = tiny_model_config(max_model_len=256)

    def make_engine(spec: bool) -> LLMEngine:
        cfg = EngineConfig(
            model=model,
            cache=CacheConfig(page_size=16, num_blocks=512, dtype="float32"),
            scheduler=SchedulerConfig(
                max_num_seqs=B, max_num_batched_tokens=B * ISL,
                speculative_ngram=spec, spec_ngram_k=K,
                spec_ngram_min_match=2,
            ),
            parallel=ParallelConfig(tensor_parallel_size=1),
            seed=0,
        )
        return LLMEngine(cfg)

    def run(workload: str) -> dict:
        rng = np.random.default_rng(0)
        if workload == "repetitive":
            sp = SamplingParams(
                temperature=0.0, max_tokens=OSL, ignore_eos=True
            )
            mk = lambda: [  # noqa: E731
                list(rng.integers(1, model.vocab_size, size=8)) * (ISL // 8)
                for _ in range(B)
            ]
        else:
            sp = SamplingParams(
                temperature=1.0, max_tokens=OSL, ignore_eos=True
            )
            mk = lambda: [  # noqa: E731
                list(rng.integers(1, model.vocab_size, size=ISL))
                for _ in range(B)
            ]
        engines = {False: make_engine(False), True: make_engine(True)}
        for eng in engines.values():  # warm, incl. mixed-split buckets
            eng.generate(mk(), sp)
            eng.generate(mk(), sp)
        sch = engines[True].scheduler
        sch.spec_accept_len_hist = [0] * (K + 1)
        sch.spec_proposed_tokens = 0
        sch.spec_accepted_tokens = 0
        # PAIRED runs: each round feeds the same fresh prompt set to
        # both engines back to back, so host drift (CI neighbors,
        # thermal) cancels in the ratio instead of dominating it.
        rates: dict[bool, list[float]] = {False: [], True: []}
        steps: dict[bool, int] = {}
        for _ in range(5):
            prompts = mk()  # fresh: no prefix-cache pollution
            for spec, eng in engines.items():
                eng.stats.engine_steps_total = 0
                t0 = time.monotonic()
                out = eng.generate([list(p) for p in prompts], sp)
                dt = time.monotonic() - t0
                total = sum(len(v) for v in out.values())
                assert total == B * OSL, (total, B * OSL)
                rates[spec].append(total / dt)
                steps[spec] = eng.stats.engine_steps_total
        res = {
            "spec_off": {
                "tok_s": round(statistics.median(rates[False]), 1),
                "steps": steps[False],
            },
            "spec_on": {
                "tok_s": round(statistics.median(rates[True]), 1),
                "steps": steps[True],
            },
            "tok_s_ratio": round(
                statistics.median(
                    on / off
                    for off, on in zip(rates[False], rates[True])
                ),
                3,
            ),
        }
        hist = sch.spec_accept_len_hist
        rows = max(sum(hist), 1)
        res["spec_on"]["accepted_len_hist"] = list(hist)
        # Mean tokens emitted per (spec row, step): 1 committed sample +
        # the accepted draft prefix. >1 means the weight read amortized
        # over more than one token.
        res["spec_on"]["mean_accepted_len"] = round(
            1 + sum(j * c for j, c in enumerate(hist)) / rows, 3
        )
        res["spec_on"]["acceptance_rate"] = round(
            sch.spec_accepted_tokens / max(sch.spec_proposed_tokens, 1), 3
        )
        return res

    out: dict = {}
    for workload in ("repetitive", "adversarial"):
        out[workload] = run(workload)
    out["substrate"] = (
        "tiny model on CPU (compute-bound): mean_accepted_len and the "
        "adversarial tok_s_ratio are the transferable numbers — "
        "repetitive wall-clock UNDERSTATES the TPU win, where decode "
        "steps are weight-read-bound and tokens/step is the speedup"
    )
    return out


def bench_spec_window():
    """Fused verify window (spec x decode_window) CPU-sim microbench:
    the SAME speculative engine at window 1 (one-shot verify, one
    dispatch per verify step) vs window 4 (K verify iterations fused,
    accept/reject on device, ONE readback per window). The headline is
    DISPATCHES PER EMITTED TOKEN — on a remote-dispatch TPU runtime the
    host round-trip per dispatch is the decode wall, so this ratio IS
    the transferable number (the CPU sim is compute-bound and its
    wall-clock understates the win). ``repetitive`` (periodic prompts,
    greedy — drafts accept, windows run hot) must show the window=4
    ratio at <= 0.5x the window=1 ratio; ``adversarial`` (random
    prompts, temperature sampling — drafts never fire, every window
    degrades to the plain fused decode program) guards the degrade
    path: its tok/s ratio must stay within noise
    (docs/architecture/speculative-decoding.md)."""
    import jax

    jax.config.update("jax_platforms", "cpu")
    import statistics

    import numpy as np

    from llmd_tpu.config import (
        CacheConfig, EngineConfig, ParallelConfig, SchedulerConfig,
        tiny_model_config,
    )
    from llmd_tpu.engine import LLMEngine, SamplingParams

    B, ISL, OSL, K = 8, 64, 64, 4
    WINDOWS = (1, 4)
    model = tiny_model_config(max_model_len=256)

    def make_engine(window: int) -> LLMEngine:
        cfg = EngineConfig(
            model=model,
            cache=CacheConfig(page_size=16, num_blocks=512, dtype="float32"),
            scheduler=SchedulerConfig(
                max_num_seqs=B, max_num_batched_tokens=B * ISL,
                speculative_ngram=True, spec_ngram_k=K,
                spec_ngram_min_match=2, decode_window=window,
            ),
            parallel=ParallelConfig(tensor_parallel_size=1),
            seed=0,
        )
        return LLMEngine(cfg)

    def run(workload: str) -> dict:
        rng = np.random.default_rng(0)
        if workload == "repetitive":
            sp = SamplingParams(
                temperature=0.0, max_tokens=OSL, ignore_eos=True
            )
            mk = lambda: [  # noqa: E731
                list(rng.integers(1, model.vocab_size, size=8)) * (ISL // 8)
                for _ in range(B)
            ]
        else:
            sp = SamplingParams(
                temperature=1.0, max_tokens=OSL, ignore_eos=True
            )
            mk = lambda: [  # noqa: E731
                list(rng.integers(1, model.vocab_size, size=ISL))
                for _ in range(B)
            ]
        engines = {w: make_engine(w) for w in WINDOWS}
        for eng in engines.values():  # warm every shape family
            eng.generate(mk(), sp)
            eng.generate(mk(), sp)
        for eng in engines.values():
            st = eng.stats
            st.decode_dispatches_total = 0
            st.generation_tokens = 0
            st.engine_steps_total = 0
            st.step_host_gap_ms_total = 0.0
            # The scheduler-side counter is what _refresh_gauges copies
            # into spec_window_iters_total — reset it too, or the
            # reported iters mix the warmup generations into the
            # measured rounds.
            eng.scheduler.spec_window_iters = 0
            eng.scheduler.spec_window_early_exit = 0
        # PAIRED rounds (see bench_spec_decode): same fresh prompts to
        # both engines back to back so host drift cancels in the ratio.
        rates: dict[int, list[float]] = {w: [] for w in WINDOWS}
        for _ in range(5):
            prompts = mk()
            for w, eng in engines.items():
                t0 = time.monotonic()
                out = eng.generate([list(p) for p in prompts], sp)
                dt = time.monotonic() - t0
                total = sum(len(v) for v in out.values())
                assert total == B * OSL, (total, B * OSL)
                rates[w].append(total / dt)
        res: dict = {}
        for w, eng in engines.items():
            st = eng.stats
            res[f"window{w}"] = {
                "tok_s": round(statistics.median(rates[w]), 1),
                "dispatches_per_token": round(
                    st.decode_dispatches_total / max(st.generation_tokens, 1),
                    4,
                ),
                "host_gap_ms_mean": round(
                    st.step_host_gap_ms_total / max(st.engine_steps_total, 1),
                    3,
                ),
                "spec_window_iters": st.spec_window_iters_total,
            }
        d1 = res["window1"]["dispatches_per_token"]
        d4 = res[f"window{WINDOWS[-1]}"]["dispatches_per_token"]
        res["dispatch_ratio"] = round(d4 / max(d1, 1e-9), 3)
        res["tok_s_ratio"] = round(
            statistics.median(
                hi / lo
                for lo, hi in zip(rates[WINDOWS[0]], rates[WINDOWS[-1]])
            ),
            3,
        )
        return res

    out: dict = {}
    for workload in ("repetitive", "adversarial"):
        out[workload] = run(workload)
    out["substrate"] = (
        "tiny model on CPU (compute-bound): dispatch_ratio (repetitive, "
        "expect <= 0.5) and the adversarial tok_s_ratio (expect >= "
        "0.95) are the transferable numbers — on an RTT-dominated TPU "
        "runtime dispatches-per-token IS the decode wall the window "
        "removes"
    )
    return out


def _bench_dbo_delta():
    """Dual-batch-overlap on/off wall-clock on the virtual 8-device CPU
    mesh (the only multi-device substrate here; real-slice numbers come
    from the same knob on hardware). Exactness is gated in
    tests/test_wide_ep.py; this records the measured step-time ratio."""
    import os

    # Must precede the first jax import (fresh subprocess via --only dbo).
    flags = os.environ.get("XLA_FLAGS", "")
    if "host_platform_device_count" not in flags:
        os.environ["XLA_FLAGS"] = (
            f"{flags} --xla_force_host_platform_device_count=8".strip()
        )
    import numpy as np

    import jax

    jax.config.update("jax_platforms", "cpu")
    import jax.numpy as jnp

    from llmd_tpu.config import ParallelConfig, tiny_model_config
    from llmd_tpu.models import llama
    from llmd_tpu.models.common import StepInput
    from llmd_tpu.parallel.mesh import build_mesh

    cfg = tiny_model_config(
        num_experts=8, num_experts_per_tok=2, hidden_size=128,
        moe_intermediate_size=128, num_layers=4, num_heads=8, num_kv_heads=4,
    )
    ctx = build_mesh(ParallelConfig(tensor_parallel_size=4, data_parallel_size=2))
    params = llama.init_params(cfg, jax.random.key(0))
    B, page, max_pages = 8, 4, 8
    kv = jnp.zeros(
        (cfg.num_layers, B * max_pages, cfg.kv_cache_heads, page,
         cfg.kv_cache_entry_dim), jnp.float32,
    )
    rng = np.random.default_rng(0)
    inp = StepInput(
        token_ids=jnp.asarray(rng.integers(1, 200, (B, 1)), jnp.int32),
        positions=jnp.full((B, 1), 5, jnp.int32),
        query_lens=jnp.ones(B, jnp.int32),
        kv_lens=jnp.full(B, 6, jnp.int32),
        page_table=jnp.arange(B * max_pages, dtype=jnp.int32).reshape(B, -1),
    )

    def step_time(dbo):
        with ctx.mesh:
            f = jax.jit(lambda p, kv: llama.forward_hidden(
                p, kv, inp, cfg, ctx.world, mesh=ctx.mesh,
                moe_backend="ep", ep_capacity_factor=8.0, dbo=dbo,
            )[0])
            f(params, kv).block_until_ready()
            samples = []
            for _ in range(10):
                t0 = time.monotonic()
                f(params, kv).block_until_ready()
                samples.append(time.monotonic() - t0)
        samples.sort()
        return samples[len(samples) // 2]

    off, on = step_time(False), step_time(True)
    return {
        "dbo_off_ms": round(off * 1e3, 2),
        "dbo_on_ms": round(on * 1e3, 2),
        "substrate": "8-dev virtual CPU mesh (dp2 x tp4, ep8)",
        # on > off here is EXPECTED, not a defect — the canonical
        # explanation lives on ParallelConfig.enable_dbo (config.py);
        # exactness is gated in tests/test_wide_ep.py.
        "note": (
            "profiled (docs/architecture/dbo.md): the split multiplies "
            "a2a ops ~3.8x on the CPU mesh with nothing to hide behind; "
            "flag is experimental, default off, gated on a real-slice win"
        ),
    }


def _moe_ep_mesh():
    """8-device virtual CPU mesh + tiny EP-MoE geometry shared by the
    moe_ep / moe_overlap parts (fresh subprocess via --only, so the
    device-count flag can still land before the first jax import)."""
    import os

    flags = os.environ.get("XLA_FLAGS", "")
    if "host_platform_device_count" not in flags:
        os.environ["XLA_FLAGS"] = (
            f"{flags} --xla_force_host_platform_device_count=8".strip()
        )
    import jax

    jax.config.update("jax_platforms", "cpu")

    from llmd_tpu.config import ParallelConfig, tiny_model_config
    from llmd_tpu.models import llama
    from llmd_tpu.parallel.mesh import build_mesh

    cfg = tiny_model_config(
        num_experts=8, num_experts_per_tok=2, hidden_size=128,
        moe_intermediate_size=64, num_layers=1, num_heads=8, num_kv_heads=4,
    )
    ctx = build_mesh(ParallelConfig(data_parallel_size=8))
    lp = {
        k: v[0]
        for k, v in llama.init_params(cfg, jax.random.key(0))["layers"].items()
        if k.startswith(("router", "we_", "ws_"))
    }
    return cfg, ctx, lp


def _bench_moe_ep():
    """Wide-EP dispatch-path CPU-sim part (wide-ep.md /
    wide-ep-perf-model.md): the three legs the perf model prices, all
    measured through the REAL ``moe_block_ep`` census on the 8-device
    virtual mesh (numerics/byte-identity are gated in
    tests/test_wide_ep.py; this records the payload/skew/drop counts
    the model predicts).

    HOT-EXPERT leg — a worst-case router (every token to experts 0+1)
    vs the same batch after the real EPLB placement
    (``compute_placement`` on the measured census, redundancy 1):
    per-destination required capacity_factor and dropped slots at
    static C=2.0, before vs after balancing — the factor-of-W/k skew
    EPLB erases.

    ADAPTIVE leg — a naturally-imbalanced router: the AdaptiveCapacity
    ladder converges on the observed demand and ships strictly fewer
    padded slots (and a2a payload bytes, 2 x W x C x H x 4 per
    microbatch both directions) than static 2.0 — both legs at ZERO
    dropped slots (the CI summary asserts this).

    FLEET leg — the expert_skew fleetsim scenario EPLB-on vs
    identity-layout on the same seeded Zipf trace: exact virtual-time
    dropped-slot and mean-shard-skew comparison plus the tail-TPOT
    ratio."""
    cfg, ctx, lp = _moe_ep_mesh()
    import numpy as np

    import jax
    import jax.numpy as jnp

    from llmd_tpu.parallel.eplb import AdaptiveCapacity, compute_placement
    from llmd_tpu.parallel.moe_ep import _capacity, moe_block_ep

    E, H, k = cfg.num_experts, cfg.hidden_size, cfg.num_experts_per_tok
    W = ctx.world
    B, T = 8, 64  # 512 tokens -> t*k/W = 128 per destination at balance
    h = jax.random.normal(jax.random.key(1), (B, T, H), jnp.float32)

    def census_of(lp, factor, placement=None, hh=None):
        with ctx.mesh:
            _, census = jax.jit(lambda h, lp: moe_block_ep(
                h, lp, cfg, ctx.mesh, capacity_factor=factor,
                placement=placement, emit_census=True,
            ))(h if hh is None else hh, lp)
        return np.asarray(census)

    # HOT-EXPERT leg: zeroed router logits tie every score, so top-k
    # routes every token to logical experts 0 and 1 — the two hottest
    # destinations take W/k = 4x the balanced flow.
    lp_hot = dict(lp)
    lp_hot["router"] = jnp.zeros_like(lp["router"])
    hot = census_of(lp_hot, 2.0)
    counts = hot[:E]
    pl = compute_placement(counts, world=W, redundancy=1)
    tables = {
        "phys_to_logical": jnp.asarray(pl.phys_to_logical),
        "replicas": jnp.asarray(pl.replicas),
        "n_replicas": jnp.asarray(pl.n_replicas),
    }
    # Physical expert weights = logical gathered through the placement
    # (the runner's we_* leaf remap at the step boundary).
    lp_bal = {
        k2: (jnp.take(v, tables["phys_to_logical"], axis=0)
             if k2.startswith("we_") else v)
        for k2, v in lp_hot.items()
    }
    balanced = census_of(lp_bal, 2.0, placement=tables)

    # ADAPTIVE leg: the natural (mildly imbalanced) router, balanced by
    # its own EPLB placement — the deployment shape. Feed the measured
    # required factor to the ladder until the down-hysteresis clears,
    # then price the padded slots / a2a bytes each factor ships.
    # Serving-sized batch: per-destination demand noise shrinks with
    # sample count, which is what lets the ladder settle under 2.0.
    Tb = 256
    h_big = jax.random.normal(jax.random.key(2), (B, Tb, H), jnp.float32)
    nat = census_of(lp, 8.0, hh=h_big)  # lossless probe: read true demand
    pl_nat = compute_placement(nat[:E], world=W, redundancy=1)
    tables_nat = {
        "phys_to_logical": jnp.asarray(pl_nat.phys_to_logical),
        "replicas": jnp.asarray(pl_nat.replicas),
        "n_replicas": jnp.asarray(pl_nat.n_replicas),
    }
    lp_nat = {
        k2: (jnp.take(v, tables_nat["phys_to_logical"], axis=0)
             if k2.startswith("we_") else v)
        for k2, v in lp.items()
    }
    need = float(census_of(lp_nat, 8.0, placement=tables_nat, hh=h_big)[E + 1])
    ladder = AdaptiveCapacity(base=2.0)
    factor = 2.0
    for _ in range(3 * ladder.hold_steps):
        nxt = ladder.observe(need)
        if nxt is not None:
            factor = nxt
    t_loc = B * Tb // W
    c_static, c_adapt = _capacity(t_loc, k, W, 2.0), _capacity(t_loc, k, W, factor)
    a2a_bytes = lambda c: 2 * W * c * H * 4  # noqa: E731  dispatch + combine
    drops_static = float(
        census_of(lp_nat, 2.0, placement=tables_nat, hh=h_big)[E]
    )
    drops_adapt = float(
        census_of(lp_nat, factor, placement=tables_nat, hh=h_big)[E]
    )

    # FLEET leg at reduced scale (the full-scale matrix runs in CI).
    from llmd_tpu.fleetsim.scenarios import build_expert_skew

    on = build_expert_skew(0, 0.25, eplb=True).run()
    off = build_expert_skew(0, 0.25, eplb=False).run()

    return {
        "geometry": f"E{E} k{k} over {W} EP shards, {B * T} tokens/step",
        "hot_required_factor": round(float(hot[E + 1]), 3),
        "hot_dropped_slots_static2": int(hot[E]),
        "eplb_required_factor": round(float(balanced[E + 1]), 3),
        "eplb_dropped_slots_static2": int(balanced[E]),
        "expert_counts_skew": round(
            float(counts.max() / max(counts.mean(), 1e-9)), 3
        ),
        "adaptive_factor": factor,
        "adaptive_required": round(need, 3),
        "padded_slots_static2": W * c_static,
        "padded_slots_adaptive": W * c_adapt,
        "a2a_mb_static2": round(a2a_bytes(c_static) / 2**20, 3),
        "a2a_mb_adaptive": round(a2a_bytes(c_adapt) / 2**20, 3),
        "dropped_slots_static2": drops_static,
        "dropped_slots_adaptive": drops_adapt,
        "fleet_dropped_on_vs_off": [
            on["expert_skew"]["dropped_slots"],
            off["expert_skew"]["dropped_slots"],
        ],
        "fleet_mean_skew_on_vs_off": [
            on["expert_skew"]["mean_shard_skew"],
            off["expert_skew"]["mean_shard_skew"],
        ],
        "fleet_tpot_p99_ratio": round(
            on["latency_ms"]["tpot"]["p99"] / off["latency_ms"]["tpot"]["p99"],
            3,
        ),
    }


def _bench_moe_overlap():
    """Microbatched overlapped expert dispatch on/off step time on the
    8-device virtual CPU mesh (wide-ep.md "overlapped dispatch").
    Byte-identity of the microbatched path is gated in
    tests/test_wide_ep.py; this records the measured ratio. Same
    graduation contract as DBO: the flag is experimental and default
    OFF until a real TPU slice shows overlap >= 2 step time strictly
    below overlap = 0 at serving batch — the falsifiable gate; on the
    CPU mesh the extra a2a dispatches have nothing to hide behind, so
    on > off here is EXPECTED, not a defect."""
    cfg, ctx, lp = _moe_ep_mesh()
    import jax
    import jax.numpy as jnp

    from llmd_tpu.parallel.moe_ep import moe_block_ep

    h = jax.random.normal(
        jax.random.key(1), (8, 64, cfg.hidden_size), jnp.float32
    )

    def step_time(overlap):
        with ctx.mesh:
            f = jax.jit(lambda h, lp: moe_block_ep(
                h, lp, cfg, ctx.mesh, capacity_factor=2.0, overlap=overlap,
            ))
            f(h, lp).block_until_ready()
            samples = []
            for _ in range(10):
                t0 = time.monotonic()
                f(h, lp).block_until_ready()
                samples.append(time.monotonic() - t0)
        samples.sort()
        return samples[len(samples) // 2]

    off, on = step_time(0), step_time(2)
    return {
        "overlap_off_ms": round(off * 1e3, 2),
        "overlap2_ms": round(on * 1e3, 2),
        "substrate": "8-dev virtual CPU mesh (dp8, ep8)",
        "note": (
            "byte-identical microbatched dispatch "
            "(tests/test_wide_ep.py); experimental, default off, "
            "graduates on a real-slice overlap-on win at serving batch"
        ),
    }


def _atomic_write_json(path: str, obj) -> None:
    """Write JSON via tmp + rename: a SIGKILL mid-write must never leave
    a torn/unparseable file (the partial stream IS the crash record)."""
    import os

    tmp = f"{path}.tmp"
    with open(tmp, "w") as f:
        json.dump(obj, f)
        f.flush()
        os.fsync(f.fileno())
    os.replace(tmp, path)


def _part_in_subprocess(part: str, retries: int = 0, timeout: float = 1800):
    import os
    import subprocess
    import sys

    last = None
    for attempt in range(retries + 1):
        proc = subprocess.run(
            [sys.executable, os.path.abspath(__file__), "--only", part],
            capture_output=True, text=True, timeout=timeout,
        )
        if proc.returncode == 0:
            return json.loads(proc.stdout.strip().splitlines()[-1])
        # Tunnel-attached chips throw transient device/fetch errors over
        # an hour-long run; the headline part gets one retry to separate
        # those from real breaks (a blanket retry would double the
        # worst-case wall clock — the r5 failure mode).
        last = RuntimeError(
            f"bench part {part} failed rc={proc.returncode}: "
            + proc.stderr[-300:]
        )
    raise last


# Parts whose substrate is the CPU sim (forced inside the part itself):
# runnable in CI / under --skip-chip without a device or the tunnel.
_CPU_PARTS = frozenset({
    "dbo", "async_step", "spec_decode", "spec_window", "unified_step",
    "ragged_step", "fault_degrade", "fleet_soak", "kv_federation",
    "stream_resume", "batch_backfill", "lora_pool", "pd_stream",
    "moe_ep", "moe_overlap", "long_context",
})

# Every part main() can dispatch, in run order (also the validation set
# for --parts: a typo'd name must fail fast, not silently run nothing).
# CHEAPEST-FIRST (VERDICT r5 job #1): the chip-free CPU-sim parts are
# guaranteed-capturable even with a wedged tunnel, the cheap chip probes
# come next, the headline leads the engine parts, and the most expensive
# multi-minute parts run last — so whenever the deadline (or the
# driver's kill) lands, the summary already holds everything cheaper.
_ALL_PARTS = (
    "ragged_step", "unified_step", "async_step", "spec_decode",
    "spec_window", "dbo", "moe_ep", "moe_overlap", "fault_degrade",
    "fleet_soak", "kv_federation",
    "stream_resume", "batch_backfill", "lora_pool", "pd_stream",
    "long_context",
    "rtt", "env", "dense_int8", "dense_bf16", "mla_moe",
    "kv_int8_long", "kv_bf16_long", "swa_ring_off", "swa_ring_on",
    "pd", "pd_int8", "pd_kvint8", "pd_local", "pd_cached", "pd_adaptive",
    "predictor",
)

# Below this much remaining deadline a part is skipped outright (and
# recorded): starting a part that cannot finish only risks dying mid-
# measurement with nothing to show for the time.
_PART_FLOOR_S = 45.0


def main() -> None:
    import os
    import signal
    import sys

    if "--only" in sys.argv:
        part = sys.argv[sys.argv.index("--only") + 1]
        print(json.dumps(_run_part(part)))
        return

    # Part selection (VERDICT r5): --parts a,b,c runs only those parts;
    # --skip-chip runs only the CPU-sim parts (CI-friendly: no tunnel,
    # no 17 sequential chip subprocesses).
    argv = sys.argv[1:]
    selected: set[str] | None = None
    if "--parts" in argv:
        selected = set(argv[argv.index("--parts") + 1].split(","))
        unknown = selected - set(_ALL_PARTS)
        if unknown:
            sys.exit(
                f"unknown bench parts {sorted(unknown)}; "
                f"known: {', '.join(_ALL_PARTS)}"
            )
    skip_chip = "--skip-chip" in argv
    # Global wall-clock deadline (VERDICT r6 job #1: the bench must be
    # un-killable). Default sits well inside the driver's kill timeout;
    # parts that cannot fit the remaining budget are skipped AND
    # recorded, so an externally killed run still leaves the last
    # complete summary line on stdout and on disk.
    deadline_s = float(os.environ.get("LLMD_BENCH_DEADLINE", 2400))
    if "--deadline" in argv:
        deadline_s = float(argv[argv.index("--deadline") + 1])
    t_start = time.monotonic()
    deadline_at = t_start + deadline_s

    state: dict = {"value": None, "extras": {}}
    extras: dict = state["extras"]

    # Parts that produced a value this run, in completion order: the
    # machine-readable line between "this part's number is from THIS
    # run" and "the run died before reaching it" — automation gates on
    # it instead of inferring from which extras keys happen to exist.
    completed: list[str] = []

    def summary() -> dict:
        v = state["value"]
        return {
            "metric": "output tokens/s/chip (llama-3.2-3b-class int8 "
            "W8A8, B=256 128in/64out, single chip, e2e engine)",
            "value": v,
            "unit": "tok/s/chip",
            "vs_baseline": (
                round(v / REFERENCE_PER_CHIP_TOKS, 3) if v else None
            ),
            "parts_completed": list(completed),
            "extras": extras,
        }

    def flush_partial() -> None:
        # Stream the evolving summary after every part, on BOTH
        # channels: an atomic tmp+rename file write (a SIGKILL mid-write
        # can never tear it) and a flushed stdout line (the driver
        # parses the LAST line of stdout, so however the run dies the
        # tail is the furthest-complete parseable summary — the fix for
        # r5's rc=124/tail:"" empty record).
        s = summary()
        try:
            _atomic_write_json("bench_partial.json", s)
        except OSError:  # pragma: no cover
            pass
        print(json.dumps(s), flush=True)

    def on_signal(signum, frame):  # pragma: no cover - timeout path
        # An hour-capped run (timeout(1) -> SIGTERM -> rc=124) must
        # still deliver every finished part on stdout, not tail: ""
        # (VERDICT r5) — AND on disk: the stdout line can be lost to a
        # closed pipe, so the signal path writes the same atomic partial
        # file the per-part flush maintains.
        extras["interrupted"] = (
            f"signal {signum}: emitting partial results"
        )
        s = summary()
        try:
            _atomic_write_json("bench_partial.json", s)
        except OSError:
            pass
        print(json.dumps(s), flush=True)
        sys.exit(128 + signum)

    signal.signal(signal.SIGTERM, on_signal)
    signal.signal(signal.SIGINT, on_signal)

    # EVERY chip touch (including the RTT probe) lives in a subprocess:
    # the tunnel chip admits one process at a time, and a parent that ever
    # initialized the TPU client would starve every child part.
    attempted: set[str] = set()

    def run(part: str, apply, group: dict | None = None) -> None:
        if selected is not None and part not in selected:
            return
        if skip_chip and part not in _CPU_PARTS:
            return
        target = extras if group is None else group
        remaining = deadline_at - time.monotonic()
        if remaining < _PART_FLOOR_S:
            # Out of budget: record the skip instead of starting a part
            # that would die mid-measurement (rc=124 with data lost).
            extras.setdefault("skipped_deadline", []).append(part)
            flush_partial()
            return
        attempted.add(part)
        try:
            apply(target, _part_in_subprocess(
                part,
                # Only the headline separates transient tunnel faults
                # from real breaks with a retry; a blanket retry doubles
                # the worst-case clock (the r5 failure mode).
                retries=1 if part == "dense_int8" else 0,
                # Per-part timeout derives from the remaining deadline:
                # no single part may eat the whole budget.
                timeout=max(min(1800.0, remaining - 15.0), 30.0),
            ))
            completed.append(part)
        except Exception as e:
            target[f"{part}_error"] = f"{type(e).__name__}: {e}"[:200]
        flush_partial()

    set_key = lambda key: lambda t, v: t.__setitem__(key, v)  # noqa: E731
    merge = lambda t, v: t.update(v)  # noqa: E731
    swa: dict = {}
    extras_key_of = {
        # part -> (apply, group target)
        "ragged_step": (set_key("ragged_step"), None),
        "unified_step": (set_key("unified_step"), None),
        "async_step": (set_key("async_step"), None),
        "spec_decode": (set_key("spec_decode"), None),
        "spec_window": (set_key("spec_window"), None),
        "dbo": (set_key("dbo"), None),
        "moe_ep": (set_key("moe_ep"), None),
        "moe_overlap": (set_key("moe_overlap"), None),
        "fault_degrade": (set_key("fault_degrade"), None),
        "fleet_soak": (set_key("fleet_soak"), None),
        "kv_federation": (set_key("kv_federation"), None),
        "stream_resume": (set_key("stream_resume"), None),
        "batch_backfill": (set_key("batch_backfill"), None),
        "lora_pool": (set_key("lora_pool"), None),
        "pd_stream": (set_key("pd_stream"), None),
        "long_context": (set_key("long_context"), None),
        "rtt": (set_key("dispatch_rtt_ms"), None),
        "env": (set_key("env"), None),
        # The headline part now also carries the MFU/roofline context:
        # the scalar stays the summary's `value`, the roofline dict
        # lands in extras next to it (and in bench_partial.json).
        "dense_int8": (
            lambda t, v: (
                state.__setitem__("value", v["tok_s"]),
                t.__setitem__("roofline_int8", v["roofline"]),
            ),
            None,
        ),
        "dense_bf16": (merge, None),
        "mla_moe": (set_key("mla_moe_tok_s"), None),
        "kv_int8_long": (merge, None),
        "kv_bf16_long": (merge, None),
        "swa_ring_off": (merge, swa),
        "swa_ring_on": (merge, swa),
        "pd": (merge, None),
        "pd_int8": (merge, None),
        "pd_kvint8": (merge, None),
        "pd_local": (merge, None),
        "pd_cached": (merge, None),
        "pd_adaptive": (merge, None),
        # Latency-predictor accuracy vs the reference's ~5% MAPE bar
        # (latency-predictor.md:58), measured on a REAL engine trace;
        # the synthetic eval rides along inside.
        "predictor": (set_key("predictor"), None),
    }
    # _ALL_PARTS is the cheapest-first run order (see its comment).
    for part in _ALL_PARTS:
        apply, group = extras_key_of[part]
        run(part, apply, group)
        if group is swa and swa and "swa_ring" not in extras:
            # Fold the group in and re-flush IMMEDIATELY: a kill during
            # the next (long) part must not lose a finished group part.
            extras["swa_ring"] = swa
            flush_partial()

    print(json.dumps(summary()))
    if "dense_int8" in attempted and state["value"] is None:
        # The headline part ran and produced nothing: the summary above
        # still carries every other part, but automation gating on the
        # exit code must not record this as a clean bench run.
        sys.exit(1)
    if not completed:
        # ZERO parts completed (every attempt failed or the deadline
        # skipped them all): the summary is hollow, and rc=0 on a hollow
        # summary is exactly how an empty bench record once passed
        # gating. Exit nonzero so automation sees a failed run.
        sys.exit(1)


if __name__ == "__main__":
    main()
